"""TpuMatcher: the full match plane — compile, walk on device, expand on host.

This is the component that stands in for the reference's
``SubscriptionCache`` → ``TenantRouteCache`` → ``TenantRouteMatcher`` pipeline
(bifromq-dist-worker .../cache/SubscriptionCache.java:59,
TenantRouteCache.java:65, TenantRouteMatcher.java:68): authoritative
subscription state lives in host-side per-tenant tries (fed by route
mutations); a compiled automaton snapshot serves batched match queries on
device; topics that exceed the fixed-shape walk (active-state overflow,
over-deep topics) fall back to the host oracle, mirroring the bounded-probe
fallback contract of the reference matcher.

Mutation → visibility (the TenantRouteCache.java:100-160 refresh-on-mutation
contract, re-designed for an immutable compiled automaton):

- Every mutation applies to the authoritative tries instantly (exact
  incarnation guards) and lands in a small **delta overlay** — per-tenant
  delta tries for adds plus a tombstone set for removes/supersedes — so it
  is visible to the *next* match call without recompiling anything.
- Serving walks the **base** compiled automaton (double-buffered device
  tables) and corrects the expansion with the overlay: tombstoned base
  matchings are suppressed, delta-trie matches are merged in, then fan-out
  caps apply to the merged set.
- A background **compaction** folds the overlay into a new base: the
  mutation log replays onto a shadow copy of the tries (so the compile
  reads a frozen snapshot while serving keeps mutating), the shadow
  compiles off-thread, and the serving thread swaps in the new tables and
  rebuilds the (now tiny) overlay from the log suffix. Staleness of the
  base is bounded by compile time; correctness never depends on it.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .. import trace
from ..utils import topic as topic_util
from .automaton import (
    CompiledTrie, GroupMatching, Matching, TokenizedTopics, compile_tries,
    tokenize,
)
from .oracle import (
    PERSISTENT_SUB_BROKER_ID, UNCAPPED_FANOUT, MatchedRoutes, Route,
    SubscriptionTrie,
)


def _pow2_batch(n: int, floor: int = 16) -> int:
    """Snap a batch size up to a power of two: every distinct batch shape
    costs an XLA compile, so live traffic must reuse a small set of
    shapes."""
    b = floor
    while b < n:
        b *= 2
    return b


def _pad_rows(a: np.ndarray, rows: int, fill=0) -> np.ndarray:
    """Pad a row-gathered array up to ``rows`` rows (escalation sub-batch
    shapes snap to powers of two so live traffic reuses XLA compiles)."""
    if a.shape[0] == rows:
        return a
    out = np.full((rows,) + a.shape[1:], fill, dtype=a.dtype)
    out[:a.shape[0]] = a
    return out

# tombstone key: (full mqtt topic filter incl. any share prefix, receiver_url)
_TombKey = Tuple[str, Tuple[int, str, str]]


def _match_cache_default() -> bool:
    import os
    return os.environ.get("BIFROMQ_MATCH_CACHE", "1").lower() \
        not in ("0", "off", "false")


class TpuMatcher:
    def __init__(self, *, max_levels: int = 16, k_states: int = 32,
                 probe_len: int = 16, device=None,
                 auto_compact: bool = True,
                 compact_threshold: int = 2048,
                 max_intervals: int = 32,
                 match_cache: Optional[bool] = None) -> None:
        self.max_levels = max_levels
        self.k_states = k_states
        self.probe_len = probe_len
        self.max_intervals = max_intervals
        self.device = device
        self.auto_compact = auto_compact
        self.compact_threshold = compact_threshold
        # authoritative state (exact guards; host fallback matches)
        self.tries: Dict[str, SubscriptionTrie] = {}
        # serving snapshot (double-buffered: swapped atomically, old tables
        # stay alive for in-flight dispatches)
        self._base_ct: Optional[CompiledTrie] = None
        self._device_trie = None
        # overlay since the base snapshot
        self._delta: Dict[str, SubscriptionTrie] = {}
        self._tomb: Dict[str, Set[_TombKey]] = {}
        self._overlay_n = 0
        # per-topic token-row cache (topics repeat — the reference's
        # TenantRouteCache bet); survives recompiles, cleared on salt change
        from .automaton import TokenCache
        self._tok_cache = TokenCache()
        # ISSUE 4 tentpole: match-RESULT cache plane in front of the device
        # walk — a repeated (tenant, topic) is a dict probe, not a
        # dispatch. Filter-aware invalidation lives in add/remove_route;
        # base rebuilds bump the generation (_install_base).
        if match_cache is None:
            match_cache = _match_cache_default()
        from .matchcache import TenantMatchCache
        self.match_cache = (TenantMatchCache(scope="matcher")
                            if match_cache else None)
        # mutation log since the shadow copy last synced; shadow is the
        # frozen snapshot source for off-thread compiles
        self._log: List[Tuple] = []
        self._shadow: Dict[str, SubscriptionTrie] = {}
        self._swap_lock = threading.Lock()
        self._pending_swap = None   # set by the compact thread
        self._compact_done = False
        self._compact_thread: Optional[threading.Thread] = None
        self.compile_count = 0      # full compiles (observability/tests)
        self.compile_time_s = 0.0   # cumulative wall time in compiles
        # ISSUE 3: compile count/time surface under /metrics "device"
        from ..obs import OBS
        OBS.device.register_matcher(self)

    def clone_empty(self) -> "TpuMatcher":
        """A fresh matcher with the same configuration — the reset-from-KV
        rebuild target (subclasses override to preserve their plumbing)."""
        return TpuMatcher(max_levels=self.max_levels, k_states=self.k_states,
                          probe_len=self.probe_len, device=self.device,
                          auto_compact=self.auto_compact,
                          compact_threshold=self.compact_threshold,
                          max_intervals=self.max_intervals,
                          match_cache=self.match_cache is not None)

    @classmethod
    def from_tries(cls, tries: Dict[str, SubscriptionTrie],
                   **kwargs) -> "TpuMatcher":
        """Seed a matcher from pre-built tries WITHOUT replaying every
        route through the mutation log/overlay (bench + tier-2 gate bulk
        loads). The trie objects are SHARED between authoritative and
        shadow state: later add/remove_route traffic stays correct (the
        shadow replay re-applies each op idempotently), but the compile
        thread then reads live tries — serve-only or serially-mutating
        workloads only."""
        m = cls(**kwargs)
        m.tries = tries
        m._shadow = tries
        m.refresh()
        return m

    # ---------------- mutation side (≈ batchAddRoute/batchRemoveRoute) -----

    def add_route(self, tenant_id: str, route: Route) -> bool:
        trie = self.tries.setdefault(tenant_id, SubscriptionTrie())
        created, effective = trie.add_effective(route)
        if not effective:  # stale-incarnation upsert: nothing changed
            return False
        op = ("add", tenant_id, route)
        self._log.append(op)
        self._overlay_record(op)
        if self.match_cache is not None:
            # filter-aware (ISSUE 4): exact filters evict one topic key,
            # wildcard filters bump the tenant epoch
            self.match_cache.invalidate(tenant_id,
                                        route.matcher.filter_levels)
        self._maybe_compact()
        return created

    def remove_route(self, tenant_id: str, matcher, receiver_url,
                     incarnation: int = 0) -> bool:
        trie = self.tries.get(tenant_id)
        if trie is None:
            return False
        removed = trie.remove(matcher, receiver_url, incarnation)
        if not removed:
            return False
        if len(trie) == 0:
            del self.tries[tenant_id]
        op = ("rm", tenant_id, matcher, receiver_url, incarnation)
        self._log.append(op)
        self._overlay_record(op)
        if self.match_cache is not None:
            self.match_cache.invalidate(tenant_id, matcher.filter_levels)
        self._maybe_compact()
        return True

    def _overlay_record(self, op: Tuple) -> None:
        """Fold one log op into the serving overlay (delta tries + tombstones).

        The single definition of the overlay semantics: an add supersedes any
        base copy (tombstone) and supplies the live version via the delta
        trie; a remove tombstones the base copy and retracts any delta copy.
        """
        if op[0] == "add":
            _, tenant, route = op
            self._delta.setdefault(tenant, SubscriptionTrie()).add(route)
            self._tomb.setdefault(tenant, set()).add(
                (route.matcher.mqtt_topic_filter, route.receiver_url))
        else:
            _, tenant, matcher, url, inc = op
            d = self._delta.get(tenant)
            if d is not None:
                d.remove(matcher, url, inc)
            self._tomb.setdefault(tenant, set()).add(
                (matcher.mqtt_topic_filter, url))
        self._overlay_n += 1

    # ---------------- compilation / compaction -----------------------------

    @property
    def overlay_size(self) -> int:
        return self._overlay_n

    def _replay_log_into_shadow(self) -> None:
        for op in self._log:
            if op[0] == "add":
                _, tenant, route = op
                self._shadow.setdefault(tenant, SubscriptionTrie()).add(route)
            else:
                _, tenant, matcher, url, inc = op
                trie = self._shadow.get(tenant)
                if trie is not None:
                    trie.remove(matcher, url, inc)
                    if len(trie) == 0:
                        del self._shadow[tenant]
        self._log.clear()

    def _compile_shadow(self) -> Tuple[CompiledTrie, object]:
        import time as _time
        t0 = _time.perf_counter()
        self.compile_count += 1
        ct = compile_tries(self._shadow, max_levels=self.max_levels,
                           probe_len=self.probe_len)
        from ..ops.match import DeviceTrie  # deferred: keeps jax optional
        dev = DeviceTrie.from_compiled(ct, device=self.device)
        self._warm_walk(ct, dev)
        self.compile_time_s += _time.perf_counter() - t0
        return ct, dev

    def _warm_walk(self, ct: CompiledTrie, dev) -> None:
        """Pre-compile the serving walk for this table's shapes at the
        smallest serving batch (16, the _pow2_batch floor).

        XLA re-compiles whenever the table SHAPES change, and an
        uncompiled walk on the serving path delays the first match by
        seconds — enough to expire a short-MESSAGE_EXPIRY will that fired
        right before it. Warming here (mutation-triggered background
        compile path) keeps the publish path jit-warm."""
        try:
            from ..ops.match import Probes, walk_routes
            tok = tokenize([["warm"]], [-1], max_levels=ct.max_levels,
                           salt=ct.salt, batch=16)
            res = walk_routes(dev, Probes.from_tokenized(
                tok, device=self.device), probe_len=ct.probe_len,
                k_states=self.k_states,
                max_intervals=self.max_intervals, esc_k=0)
            np.asarray(res.overflow)
        except Exception:  # noqa: BLE001 — warm-up is best-effort
            pass

    def refresh(self) -> CompiledTrie:
        """Blocking compaction: fold every pending mutation into a fresh base.

        Kept for cold start, tests, and explicit quiesce; live mutations use
        the background path (``_maybe_compact``) instead.
        """
        self.drain()
        if self._log or self._base_ct is None:
            self._replay_log_into_shadow()
            ct, dev = self._compile_shadow()
            self._install_base(ct, dev)
        return self._base_ct

    def _install_base(self, ct: CompiledTrie, dev) -> None:
        self._base_ct = ct
        self._device_trie = dev
        # overlay = mutations not in this base = the log suffix
        self._delta = {}
        self._tomb = {}
        self._overlay_n = 0
        for op in self._log:
            self._overlay_record(op)
        # ISSUE 4: a base rebuild (overlay compaction / salt-change
        # recompile) invalidates every tenant's cached results wholesale —
        # serving stays exact either way, this is the conservative mirror
        # of the reference's refresh-on-rebuild discipline
        if self.match_cache is not None:
            self.match_cache.bump_all()

    def _maybe_compact(self, force: bool = False) -> None:
        # trigger on the FIRST mutation too (base is None): the first base
        # builds in the background so the first publish finds trie tables
        # AND the walk jit already warm, instead of paying both compiles
        # inline (the reference's refresh-on-mutation contract,
        # TenantRouteCache.java:100). ``force`` recompiles regardless of
        # overlay size (shard re-placement: new pins need a new build).
        if (self._compact_thread is not None
                or (not force
                    and (not self.auto_compact
                         or (self._base_ct is not None
                             and self._overlay_n < self.compact_threshold)))):
            self._apply_pending_swap()
            return
        # snapshot: fold the log into the shadow NOW (serving thread, cheap —
        # O(log)); the compile thread then reads only the frozen shadow
        self._replay_log_into_shadow()

        def work():
            try:
                result = self._compile_shadow()
            except Exception:  # noqa: BLE001 — must not wedge compaction
                import logging
                logging.getLogger(__name__).exception(
                    "background compaction failed; will retry")
                result = None
            with self._swap_lock:
                self._pending_swap = result
                self._compact_done = True

        self._compact_done = False
        t = threading.Thread(target=work, name="tpu-matcher-compact",
                             daemon=True)
        self._compact_thread = t
        t.start()

    def _apply_pending_swap(self) -> None:
        with self._swap_lock:
            pending, self._pending_swap = self._pending_swap, None
            done = self._compact_done
        if pending is not None:
            self._install_base(*pending)
        if done:
            # thread finished (successfully or not): allow the next compact
            self._compact_thread = None
            self._compact_done = False

    def drain(self) -> None:
        """Wait for any in-flight compaction and apply its result."""
        t = self._compact_thread
        if t is not None:
            t.join()
        self._apply_pending_swap()

    @property
    def compiled(self) -> CompiledTrie:
        return self.refresh()

    @property
    def device_trie(self):
        self.refresh()
        return self._device_trie

    # ---------------- query side (≈ SubscriptionCache.get) -----------------

    def match_batch(self, queries: Sequence[Tuple[str, Sequence[str]]],
                    *, max_persistent_fanout: int = UNCAPPED_FANOUT,
                    max_group_fanout: int = UNCAPPED_FANOUT,
                    batch: Optional[int] = None,
                    **device_kw) -> List[MatchedRoutes]:
        """The cache-plane front-end (ISSUE 4, ≈ SubscriptionCache.get →
        TenantRouteCache): per-query cache probe, then in-batch dedup so N
        identical (tenant, topic) rows walk ONCE — only the unique misses
        reach ``_match_batch_device``, so hits also shrink the padded
        device batch. Cached/fanned-out results are shared objects and
        must be treated read-only by callers (the established contract of
        the dist pub cache)."""
        if not queries:
            return []
        cache = self.match_cache
        if cache is None:
            return self._match_batch_device(
                queries, max_persistent_fanout=max_persistent_fanout,
                max_group_fanout=max_group_fanout, batch=batch, **device_kw)
        # fold any finished background compaction in BEFORE probing: its
        # generation bump must land before this batch's token snapshots,
        # not mid-walk (which would refuse every put of the batch)
        self._apply_pending_swap()
        caps = (max_persistent_fanout, max_group_fanout)
        out: List[Optional[MatchedRoutes]] = [None] * len(queries)
        uniq: Dict[Tuple[str, Tuple[str, ...]], int] = {}
        uniq_queries: List[Tuple[str, Sequence[str]]] = []
        miss_rows: List[Tuple[int, int]] = []   # (query idx, unique pos)
        for qi, (tenant_id, levels) in enumerate(queries):
            key = tuple(levels)
            m = cache.get(tenant_id, key, caps)
            if m is not None:
                out[qi] = m
                continue
            uk = (tenant_id, key)
            pos = uniq.get(uk)
            if pos is None:
                pos = uniq[uk] = len(uniq_queries)
                uniq_queries.append((tenant_id, levels))
            miss_rows.append((qi, pos))
        if uniq_queries:
            # snapshot invalidation tokens BEFORE the walk: this path is
            # synchronous, but the discipline has ONE definition — a
            # mutation landing mid-match must defeat the store (the dist
            # service's awaited path genuinely races)
            tokens = {t: cache.token(t)
                      for t in {q[0] for q in uniq_queries}}
            res = self._match_batch_device(
                uniq_queries, max_persistent_fanout=max_persistent_fanout,
                max_group_fanout=max_group_fanout, batch=batch, **device_kw)
            for (tenant_id, key), pos in uniq.items():
                cache.put(tenant_id, key, caps, res[pos],
                          tokens[tenant_id])
            for qi, pos in miss_rows:
                out[qi] = res[pos]
        # global section totals: ONE locked inc per batch, not per row.
        # Per-tenant OBS hit rates are fed by the PUB plane alone
        # (dist/service.py) — recording both planes into one window made
        # the /tenants number interpretable as neither.
        from ..utils.metrics import MATCH_CACHE
        MATCH_CACHE.inc(cache.scope, "hits",
                        len(queries) - len(miss_rows))
        MATCH_CACHE.inc(cache.scope, "misses", len(miss_rows))
        if uniq_queries:
            MATCH_CACHE.record_dedup(len(uniq_queries),
                                     len(miss_rows) - len(uniq_queries))
        return out

    def _match_batch_device(self, queries: Sequence[Tuple[str,
                                                          Sequence[str]]],
                            *, max_persistent_fanout: int = UNCAPPED_FANOUT,
                            max_group_fanout: int = UNCAPPED_FANOUT,
                            batch: Optional[int] = None
                            ) -> List[MatchedRoutes]:
        """Match (tenant_id, topic_levels) pairs; returns per-query routes.

        Exact at every instant: base walk ⊕ overlay ⊖ tombstones equals a
        match against the authoritative tries.

        The device emits matched-slot INTERVALS (ops.match.walk_routes, the
        compressed MatchedRoutes form) with overflow escalation fused into
        the same jit call; the host expands all rows with one vectorized
        ragged-arange (ops.match.expand_intervals) — never a per-slot
        Python loop (the c4 92-filters/s failure mode, VERDICT r4 #2).
        """
        from ..ops.match import Probes, expand_intervals, walk_routes

        if not queries:
            return []
        self._apply_pending_swap()
        if self._base_ct is None:
            self.refresh()
        ct = self._base_ct
        if batch is None:
            batch = _pow2_batch(len(queries))
        roots = [ct.root_of(t) for t, _ in queries]
        tok = tokenize([levels for _, levels in queries], roots,
                       max_levels=ct.max_levels, salt=ct.salt, batch=batch,
                       cache=self._tok_cache)
        probes = Probes.from_tokenized(tok, device=self.device)
        # esc_k=0: escalation stays a SEPARATE lazily-compiled dispatch
        # below — fusing it into this jit (like the bench does) would
        # compile the high-K escalation walk on the first serving query,
        # doubling cold-start latency for a pass that almost never runs
        # dispatch vs device time split (ISSUE 2): walk_routes returns as
        # soon as the device work is ENQUEUED; only the readback below
        # truly synchronizes (block_until_ready is a no-op on the axon
        # tunnel backend) — two spans attribute host dispatch cost apart
        # from real device walk time
        with trace.span("device.dispatch", batch=batch,
                        queries=len(queries)):
            res = walk_routes(self._device_trie, probes,
                              probe_len=ct.probe_len,
                              k_states=self.k_states,
                              max_intervals=self.max_intervals, esc_k=0)
        # writable copies: escalation patches rescued rows in place (a
        # bare asarray view of a jax buffer is read-only)
        with trace.span("device.sync"):
            overflow = np.array(res.overflow)
            starts_a = np.array(res.start)
            counts_a = np.array(res.count)

        # host-triggered escalation: rows whose active set (or interval
        # budget) overflowed re-walk in one compacted sub-batch at a
        # higher state budget AND a wider interval budget (a separate
        # dispatch, so its lane width is free to differ — the host merges
        # by slot arrays) — only rows that overflow even that fall
        # through to the host oracle
        esc_k = min(4 * self.k_states, 128)
        # never narrower than the base budget (a narrower re-walk is
        # guaranteed-futile for interval overflows)
        esc_a = max(min(4 * self.max_intervals, 256), self.max_intervals)
        esc_slots = {}
        ovf_rows = np.nonzero(overflow[:len(queries)]
                              & (tok.lengths[:len(queries)] >= 0))[0]
        if len(ovf_rows) and (esc_k > self.k_states
                              or esc_a > self.max_intervals):
            eb = _pow2_batch(len(ovf_rows))
            sub = Probes.from_tokenized(TokenizedTopics(
                tok_h1=_pad_rows(tok.tok_h1[ovf_rows], eb),
                tok_h2=_pad_rows(tok.tok_h2[ovf_rows], eb),
                lengths=_pad_rows(tok.lengths[ovf_rows], eb, fill=-1),
                roots=_pad_rows(tok.roots[ovf_rows], eb, fill=-1),
                sys_mask=_pad_rows(tok.sys_mask[ovf_rows], eb),
            ), device=self.device)
            res2 = walk_routes(self._device_trie, sub,
                               probe_len=ct.probe_len, k_states=esc_k,
                               max_intervals=esc_a, esc_k=0)
            o2 = np.asarray(res2.overflow)
            slots2, offs2 = expand_intervals(res2.start, res2.count)
            for j, qi in enumerate(ovf_rows):
                if not o2[j]:
                    esc_slots[int(qi)] = slots2[offs2[j]:offs2[j + 1]]
                    overflow[qi] = False
        slots, offs = expand_intervals(starts_a, counts_a)
        out: List[MatchedRoutes] = []
        for qi, (tenant_id, levels) in enumerate(queries):
            tomb = self._tomb.get(tenant_id)
            delta = self._delta.get(tenant_id)
            if roots[qi] < 0:
                # tenant absent from the base snapshot: all its routes (if
                # any) are newer than the base — serve from authoritative
                out.append(self.match_from_tries(
                    [(tenant_id, levels)],
                    max_persistent_fanout=max_persistent_fanout,
                    max_group_fanout=max_group_fanout)[0])
                continue
            if overflow[qi] or tok.lengths[qi] < 0:
                # even the fused device escalation overflowed (or the topic
                # is too deep for the walk shape): host oracle re-match
                out.append(self.match_from_tries(
                    [(tenant_id, levels)],
                    max_persistent_fanout=max_persistent_fanout,
                    max_group_fanout=max_group_fanout)[0])
                continue
            row = (esc_slots[qi] if qi in esc_slots
                   else slots[offs[qi]:offs[qi + 1]])
            if not tomb and delta is None:
                # fast path: no overlay for this tenant
                out.append(self._routes_from_slots(
                    ct, row, max_persistent_fanout, max_group_fanout))
                continue
            out.append(self._expand_with_overlay(
                ct, row, tomb or (), delta, list(levels),
                max_persistent_fanout, max_group_fanout))
        return out

    def match(self, tenant_id: str, topic: str, **kwargs) -> MatchedRoutes:
        return self.match_batch([(tenant_id, topic_util.parse(topic))],
                                **kwargs)[0]

    def match_from_tries(self, queries: Sequence[Tuple[str, Sequence[str]]],
                         *, max_persistent_fanout: int = UNCAPPED_FANOUT,
                         max_group_fanout: int = UNCAPPED_FANOUT
                         ) -> List[MatchedRoutes]:
        """Match straight from the authoritative host tries — the ONE
        exact-oracle fallback surface, shared by the walk's overflow path
        and the dist worker's fault/deadline degradation path (keeping
        their semantics identical by construction)."""
        out: List[MatchedRoutes] = []
        for tenant_id, levels in queries:
            trie = self.tries.get(tenant_id)
            out.append(trie.match(
                list(levels), max_persistent_fanout=max_persistent_fanout,
                max_group_fanout=max_group_fanout)
                if trie is not None else MatchedRoutes())
        return out

    @staticmethod
    def _routes_from_slots(ct: CompiledTrie, row: np.ndarray,
                           max_persistent_fanout: int,
                           max_group_fanout: int) -> MatchedRoutes:
        """Slot ids → MatchedRoutes, caps applied vectorized.

        Same cap semantics as _expand (MatchedRoutes.java:38 rules) but all
        per-slot work is numpy: kind masks + cumsum ranks instead of a
        Python loop over slots. Group filters are unique per topic (one
        GroupMatching slot per (node, filter)), so a rank cutoff equals the
        reference's distinct-filter cap.
        """
        out = MatchedRoutes()
        if row.size == 0:
            return out
        kinds = ct.slot_kind[row]
        pers_mask = kinds == CompiledTrie.SLOT_PERSISTENT
        if (max_persistent_fanout != UNCAPPED_FANOUT
                and int(pers_mask.sum()) > max_persistent_fanout):
            out.max_persistent_fanout_exceeded = True
            drop = pers_mask & (np.cumsum(pers_mask)
                                > max_persistent_fanout)
            row, kinds, pers_mask = (row[~drop], kinds[~drop],
                                     pers_mask[~drop])
        out.persistent_fanout = int(pers_mask.sum())
        grp_mask = kinds == CompiledTrie.SLOT_GROUP
        arr = ct.matchings_arr
        if grp_mask.any():
            grp_slots = row[grp_mask]
            if (max_group_fanout != UNCAPPED_FANOUT
                    and grp_slots.size > max_group_fanout):
                out.max_group_fanout_exceeded = True
                grp_slots = grp_slots[:max_group_fanout]
            for m in arr[grp_slots]:
                out.groups[m.mqtt_topic_filter] = list(m.members)
            out.normal = arr[row[~grp_mask]].tolist()
        else:
            out.normal = arr[row].tolist()
        return out

    def _expand_with_overlay(self, ct: CompiledTrie, slots: np.ndarray,
                             tomb, delta: Optional[SubscriptionTrie],
                             levels: List[str],
                             max_persistent_fanout: int,
                             max_group_fanout: int) -> MatchedRoutes:
        """Base expansion ⊖ tombstones ⊕ delta matches, then caps.

        ``slots`` are matched slot ids from the interval walk (single-chip
        and mesh paths both expand intervals before calling)."""
        normal: List[Route] = []
        groups: Dict[str, List[Route]] = {}
        for slot in (int(s) for s in slots):
            m: Matching = ct.matchings[slot]
            if isinstance(m, GroupMatching):
                members = [r for r in m.members
                           if (m.mqtt_topic_filter, r.receiver_url)
                           not in tomb]
                if members:
                    groups[m.mqtt_topic_filter] = members
            else:
                if (m.matcher.mqtt_topic_filter, m.receiver_url) not in tomb:
                    normal.append(m)
        if delta is not None:
            dm = delta.match(levels)
            normal.extend(dm.normal)
            for f, members in dm.groups.items():
                groups.setdefault(f, []).extend(members)
        # caps over the merged set (MatchedRoutes.java:38 rules)
        out = MatchedRoutes()
        for r in normal:
            if r.broker_id == PERSISTENT_SUB_BROKER_ID:
                if out.persistent_fanout >= max_persistent_fanout:
                    out.max_persistent_fanout_exceeded = True
                    continue
                out.persistent_fanout += 1
            out.normal.append(r)
        for f, members in groups.items():
            if len(out.groups) >= max_group_fanout:
                out.max_group_fanout_exceeded = True
                continue
            out.groups[f] = members
        return out
