"""TpuMatcher: the full match plane — compile, walk on device, expand on host.

This is the component that stands in for the reference's
``SubscriptionCache`` → ``TenantRouteCache`` → ``TenantRouteMatcher`` pipeline
(bifromq-dist-worker .../cache/SubscriptionCache.java:59,
TenantRouteCache.java:65, TenantRouteMatcher.java:68): authoritative
subscription state lives in host-side per-tenant tries (fed by route
mutations); a compiled automaton snapshot serves batched match queries on
device; topics that exceed the fixed-shape walk (active-state overflow,
over-deep topics) fall back to the host oracle, mirroring the bounded-probe
fallback contract of the reference matcher.

Mutation → visibility (the TenantRouteCache.java:100-160 refresh-on-mutation
contract, re-designed for an immutable compiled automaton):

- Every mutation applies to the authoritative tries instantly (exact
  incarnation guards) and lands in a small **delta overlay** — per-tenant
  delta tries for adds plus a tombstone set for removes/supersedes — so it
  is visible to the *next* match call without recompiling anything.
- Serving walks the **base** compiled automaton (double-buffered device
  tables) and corrects the expansion with the overlay: tombstoned base
  matchings are suppressed, delta-trie matches are merged in, then fan-out
  caps apply to the merged set.
- A background **compaction** folds the overlay into a new base: the
  mutation log replays onto a shadow copy of the tries (so the compile
  reads a frozen snapshot while serving keeps mutating), the shadow
  compiles off-thread, and the serving thread swaps in the new tables and
  rebuilds the (now tiny) overlay from the log suffix. Staleness of the
  base is bounded by compile time; correctness never depends on it.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .. import trace
from ..utils.metrics import STAGES
from ..utils import topic as topic_util
from .automaton import (
    CompiledTrie, GroupMatching, Matching, PatchableTrie, PatchFallback,
    compile_tries, patch_enabled, tokenize,
)
from .oracle import (
    PERSISTENT_SUB_BROKER_ID, UNCAPPED_FANOUT, MatchedRoutes, Route,
    SubscriptionTrie,
)


def _pow2_batch(n: int, floor: int = 16) -> int:
    """Snap a batch size up to a power of two: every distinct batch shape
    costs an XLA compile, so live traffic must reuse a small set of
    shapes."""
    b = floor
    while b < n:
        b *= 2
    return b


def _parse_levels(levels) -> List[str]:
    """Queries carry the raw topic — str or wire ``bytes`` (ISSUE 11
    byte plane: the serving path ships bytes to the tokenizer and only
    the rare fallback/overlay paths materialize level lists) — or a
    pre-parsed level sequence; normalize to a level-string list at the
    point of use."""
    if isinstance(levels, bytes):
        levels = levels.decode("utf-8")
    if isinstance(levels, str):
        return topic_util.parse(levels)
    return list(levels)


def _query_key(levels):
    """Cache/dedup key of a query's topic half: the raw string (or wire
    bytes) is its own key (no re-join, no tuple build); level lists
    keep the tuple form."""
    if isinstance(levels, (str, bytes)):
        return levels
    return tuple(levels)

# tombstone key: (full mqtt topic filter incl. any share prefix, receiver_url)
_TombKey = Tuple[str, Tuple[int, str, str]]


class _Prepared:
    """Stage-1 output (ISSUE 11): a tokenized + uploaded probe batch,
    built BEFORE ring admission so batch N+1's prep overlaps batch N's
    walk. Holds the base snapshot it tokenized against — the dispatch
    half re-preps iff a compaction swapped the base in the gap (roots
    and salt are per-snapshot)."""

    __slots__ = ("queries", "ct", "tok", "probes", "roots", "batch",
                 "tokenize_s")

    def __init__(self, **kw) -> None:
        for k, v in kw.items():
            setattr(self, k, v)


class _InFlight:
    """Captured dispatch state for one device batch (ISSUE 6 pipeline).

    The expansion step (sync or async-on-ready) must run against the
    SNAPSHOT the walk dispatched on — the base tables and the overlay
    dict *objects* captured here — never re-read ``self._base_ct``: a
    background compaction swapping mid-flight replaces the overlay dicts
    with the (empty) log-suffix rebuild, and expanding old-base slots
    with the new overlay would drop every mutation the compaction folded.
    Holding the old dict objects keeps them alive and still-mutating
    (pre-swap mutations land in them in place), which is exactly the
    state the old base needs.
    """

    __slots__ = ("queries", "ct", "dev", "tok", "roots", "res", "tomb",
                 "delta", "batch", "kernel", "fault", "dispatch_s",
                 "tokenize_s", "dev_expand_s", "peer_tab")

    def __init__(self, **kw) -> None:
        self.fault = None   # fired device FaultRule (ISSUE 7 chaos hook)
        self.dispatch_s = 0.0  # dispatch-stage seconds (ISSUE 8 profiler)
        self.tokenize_s = 0.0  # stage-1 prep seconds (ISSUE 11 profiler)
        self.dev_expand_s = 0.0  # device-expand enqueue (ISSUE 19)
        self.peer_tab = None     # PeerTable the expansion bucketed against
        for k, v in kw.items():
            setattr(self, k, v)


class _HostPairs:
    """Host view of one device-expanded batch (ISSUE 19): the compact
    (slot, row) pair buffers + peer buckets ``_fetch_walk`` read back,
    plus the in-flight result object for the lazy grid fetch that only
    buffer-truncated rows need."""

    __slots__ = ("slots", "rows", "row_offsets", "n_pairs", "trunc",
                 "peer_slots", "peer_rows", "peer_offsets", "res")

    def __init__(self, **kw) -> None:
        for k, v in kw.items():
            setattr(self, k, v)


def _match_cache_default() -> bool:
    from ..utils.env import env_bool
    return env_bool("BIFROMQ_MATCH_CACHE", True)


def apply_log_op(tries: Dict[str, SubscriptionTrie], op: Tuple) -> None:
    """Apply ONE matcher log op to a tries dict — THE single definition
    of the op → trie semantics, shared by the shadow replay and the
    replication standby's authoritative-trie upkeep (ISSUE 12): the two
    sides must never drift, or standby host-oracle parity silently
    breaks."""
    if op[0] == "add":
        _, tenant, route = op
        tries.setdefault(tenant, SubscriptionTrie()).add(route)
    elif op[0] == "rm":
        _, tenant, matcher, url, inc = op
        trie = tries.get(tenant)
        if trie is not None:
            trie.remove(matcher, url, inc)
            if len(trie) == 0:
                del tries[tenant]


def _safe_hook(cb, what: str, *args) -> None:
    """Fire an optional observer hook without letting it poison the
    mutation/install path (ISSUE 12: delta/rebase emit chains)."""
    if cb is None:
        return
    try:
        cb(*args)
    except Exception:  # noqa: BLE001 — observers must not break serving
        logging.getLogger(__name__).exception("%s hook failed", what)


class TpuMatcher:
    # the async pipeline path (match_batch_async) drives _dispatch_device
    # directly; subclasses replacing the whole device plane (MeshMatcher)
    # flip this off and the async entry degrades to their sync path
    supports_async = True
    # ISSUE 9: single-chip bases are PatchableTrie and mutations fold into
    # the arenas in place (delta patches + narrow device updates) instead
    # of accumulating in the overlay until a full rebuild. Subclasses
    # whose compile target isn't the single-chip CompiledTrie (MeshMatcher
    # ships per-shard stacks to a mesh) flip this off and keep the
    # overlay+compaction path — per-shard patching is the ROADMAP
    # follow-up this PR unlocks.
    supports_patching = True

    def __init__(self, *, max_levels: int = 16, k_states: int = 32,
                 probe_len: int = 16, device=None,
                 auto_compact: bool = True,
                 compact_threshold: int = 2048,
                 max_intervals: int = 32,
                 match_cache: Optional[bool] = None) -> None:
        self.max_levels = max_levels
        self.k_states = k_states
        self.probe_len = probe_len
        self.max_intervals = max_intervals
        self.device = device
        self.auto_compact = auto_compact
        self.compact_threshold = compact_threshold
        # authoritative state (exact guards; host fallback matches)
        self.tries: Dict[str, SubscriptionTrie] = {}
        # serving snapshot (double-buffered: swapped atomically, old tables
        # stay alive for in-flight dispatches)
        self._base_ct: Optional[CompiledTrie] = None
        self._device_trie = None
        # overlay since the base snapshot
        self._delta: Dict[str, SubscriptionTrie] = {}
        self._tomb: Dict[str, Set[_TombKey]] = {}
        self._overlay_n = 0
        # per-topic token-row cache (topics repeat — the reference's
        # TenantRouteCache bet); survives recompiles, cleared on salt change
        from .automaton import TokenCache
        self._tok_cache = TokenCache()
        # ISSUE 4 tentpole: match-RESULT cache plane in front of the device
        # walk — a repeated (tenant, topic) is a dict probe, not a
        # dispatch. Filter-aware invalidation lives in add/remove_route;
        # base rebuilds bump the generation (_install_base).
        if match_cache is None:
            match_cache = _match_cache_default()
        from .matchcache import TenantMatchCache
        self.match_cache = (TenantMatchCache(scope="matcher")
                            if match_cache else None)
        # ISSUE 6: async dispatch ring (lazy — sync-only deployments never
        # pay for it); see models/pipeline.py for the knobs
        self._ring = None
        # ISSUE 7: per-device circuit breaker fed by device timeouts and
        # errors — open serves the exact host-oracle degraded path with
        # no dispatch at all, half-open admits ONE canary batch that
        # re-closes only on row parity with the oracle. Registered on
        # the process-global board so /metrics "fabric.breakers" and the
        # gossip health digest see it.
        from ..resilience.device import (DEVICE_BREAKERS,
                                         device_breaker_enabled)
        self.device_breaker = (DEVICE_BREAKERS.create()
                               if device_breaker_enabled() else None)
        # ISSUE 12 replication emit hooks (armed by DistWorkerCoProc):
        # on_delta(tenant, filter_levels, op, plan, fallback) fires per
        # applied mutation with the captured PatchPlan (None when the op
        # went to the overlay); on_rebase(salt, reason) fires on every
        # COMPILED base install — arenas renumbered, the delta stream
        # must re-anchor. _replaying suppresses emission while a replay
        # (log suffix / reset-from-KV rebuild) re-applies ops that were
        # already streamed (or are covered by an anchor).
        self.on_delta = None
        self.on_rebase = None
        self._replaying = False
        # mutation log since the shadow copy last synced; shadow is the
        # frozen snapshot source for off-thread compiles
        self._log: List[Tuple] = []
        self._shadow: Dict[str, SubscriptionTrie] = {}
        self._swap_lock = threading.Lock()
        self._pending_swap = None   # set by the compact thread
        self._compact_done = False
        self._compact_thread: Optional[threading.Thread] = None
        # ISSUE 10: background patch-scatter warm (joinable by tests)
        self._scatter_warm_thread: Optional[threading.Thread] = None
        self.compile_count = 0      # full compiles (observability/tests)
        self.compile_time_s = 0.0   # cumulative wall time in compiles
        # ISSUE 19 device fan-out: slot→delivery-peer table cache, keyed
        # on base-snapshot identity (rebuilt per compile, NEVER per patch
        # flush — slots patched in after the build land in the UNKNOWN
        # bucket and get exact host grouping, so staleness is a fast-path
        # miss, not a correctness risk). last_expanded is the observability
        # surface for the most recent device-bucketed batch (bench/tests).
        self._peer_cache: Optional[Tuple] = None
        self.last_expanded = None
        # ISSUE 9 patch-plane accounting (mutations folded into the base
        # in place vs ops that fell back to the overlay)
        self.patch_count = 0        # mutations applied as in-place patches
        self.patch_fallbacks = 0    # ops the patcher refused (overlay'd)
        self.patch_flushes = 0      # device patch-update rounds
        self.patch_host_s = 0.0     # cumulative host plan+arena time
        self.patch_device_s = 0.0   # cumulative device update time
        # ISSUE 8 compile-event ledger: what triggered the build the
        # NEXT _install_base lands (first_base / threshold / forced /
        # refresh), and how long that compile ran
        self._compile_reason = "first_base"
        self._last_compile_s = 0.0
        # ISSUE 3: compile count/time surface under /metrics "device"
        from ..obs import OBS
        OBS.device.register_matcher(self)

    def clone_empty(self) -> "TpuMatcher":
        """A fresh matcher with the same configuration — the reset-from-KV
        rebuild target (subclasses override to preserve their plumbing)."""
        return TpuMatcher(max_levels=self.max_levels, k_states=self.k_states,
                          probe_len=self.probe_len, device=self.device,
                          auto_compact=self.auto_compact,
                          compact_threshold=self.compact_threshold,
                          max_intervals=self.max_intervals,
                          match_cache=self.match_cache is not None)

    @classmethod
    def from_tries(cls, tries: Dict[str, SubscriptionTrie],
                   **kwargs) -> "TpuMatcher":
        """Seed a matcher from pre-built tries WITHOUT replaying every
        route through the mutation log/overlay (bench + tier-2 gate bulk
        loads). The trie objects are SHARED between authoritative and
        shadow state: later add/remove_route traffic stays correct (the
        shadow replay re-applies each op idempotently), but the compile
        thread then reads live tries — serve-only or serially-mutating
        workloads only."""
        m = cls(**kwargs)
        m.tries = tries
        m._shadow = tries
        m.refresh()
        return m

    # ---------------- mutation side (≈ batchAddRoute/batchRemoveRoute) -----

    def add_route(self, tenant_id: str, route: Route) -> bool:
        trie = self.tries.setdefault(tenant_id, SubscriptionTrie())
        created, effective = trie.add_effective(route)
        if not effective:  # stale-incarnation upsert: nothing changed
            return False
        op = ("add", tenant_id, route)
        self._log.append(op)
        plan, fallback = self._fold_op(op)
        if self.match_cache is not None:
            # filter-aware (ISSUE 4): exact filters evict one topic key,
            # wildcard filters bump the tenant epoch
            self.match_cache.invalidate(tenant_id,
                                        route.matcher.filter_levels)
        self._emit_delta(tenant_id, route.matcher.filter_levels, op,
                         plan, fallback)
        self._maybe_compact()
        return created

    def remove_route(self, tenant_id: str, matcher, receiver_url,
                     incarnation: int = 0) -> bool:
        trie = self.tries.get(tenant_id)
        if trie is None:
            return False
        removed = trie.remove(matcher, receiver_url, incarnation)
        if not removed:
            return False
        if len(trie) == 0:
            del self.tries[tenant_id]
        op = ("rm", tenant_id, matcher, receiver_url, incarnation)
        self._log.append(op)
        plan, fallback = self._fold_op(op)
        if self.match_cache is not None:
            self.match_cache.invalidate(tenant_id, matcher.filter_levels)
        self._emit_delta(tenant_id, matcher.filter_levels, op, plan,
                         fallback)
        self._maybe_compact()
        return True

    # ---------------- incremental patching (ISSUE 9 tentpole) --------------

    def _fold_op(self, op: Tuple):
        """Patch-first fold of one log op, with PatchPlan capture when a
        delta subscriber is armed (ISSUE 12): the physical write set the
        leader just executed is EXACTLY what a byte-identical replica
        applies — no second descent, no hashing. Returns
        ``(plan, fallback)``; a declined op records into the overlay and
        ships op-only (a fallback may still carry a PARTIAL plan: nodes
        allocated before the patcher refused stay in the arena as
        garbage, and the replica mirrors them to keep byte parity)."""
        base = self._base_ct
        record = (self.on_delta is not None and not self._replaying
                  and isinstance(base, PatchableTrie))
        if record:
            base.begin_plan()
        try:
            ok = self._try_patch(op)
        finally:
            plan = base.take_plan() if record else None
        if not ok:
            # no patchable base (or the op fell back): serve it from the
            # delta overlay until the next compaction folds it in
            self._overlay_record(op)
        if plan is not None and plan.empty and not ok:
            plan = None
        return plan, not ok

    def _emit_delta(self, tenant_id, filter_levels, op, plan,
                    fallback) -> None:
        if not self._replaying:
            _safe_hook(self.on_delta, "delta emit", tenant_id,
                       filter_levels, op, plan, fallback)

    def _patching_enabled(self) -> bool:
        return self.supports_patching and patch_enabled()

    def _group_members(self, tenant_id: str, matcher) -> dict:
        """The authoritative surviving member set for a shared-group op —
        the patcher replaces the whole GroupMatching slot with it (group
        member churn is a pure host-side object swap, zero device
        traffic)."""
        trie = self.tries.get(tenant_id)
        node = trie._root if trie is not None else None
        for level in matcher.filter_levels:
            if node is None:
                return {}
            node = node.children.get(level)
        if node is None:
            return {}
        gkey = (int(matcher.type), matcher.group or "")
        return dict(node.groups.get(gkey, {}))

    def _patch_targets(self, tenant_id: str) -> list:
        """The PatchableTrie arena(s) a mutation for this tenant folds
        into — the single-chip base itself; the mesh subclass routes to
        the tenant's shard(s) (every shard for a replicated hot tenant).
        Empty when there is nothing to patch (no base yet, kill-switch,
        non-patchable compile target)."""
        base = self._base_ct
        if base is None or not isinstance(base, PatchableTrie) \
                or not self._patching_enabled():
            return []
        return [base]

    def _try_patch(self, op: Tuple) -> bool:
        """Fold one log op straight into the installed base arenas.

        Returns False when there is nothing to patch (no base yet, env
        kill-switch) or the patcher declined (``PatchFallback``) — the
        caller then records the op into the overlay, exactly the
        pre-patching serving path. A multi-target fold (replicated mesh
        tenant) that declines mid-way is safe: the patch methods are
        find-or-append idempotent and the overlay record supersedes the
        partially-patched copies exactly like a base copy.
        """
        targets = self._patch_targets(op[1])
        if not targets:
            return False
        from ..types import RouteMatcherType
        t0 = time.perf_counter()
        try:
            if op[0] == "add":
                _, tenant_id, route = op
                gm = None
                if route.matcher.type != RouteMatcherType.NORMAL:
                    gm = self._group_members(tenant_id, route.matcher)
                for base in targets:
                    base.patch_add(tenant_id, route, group_members=gm)
            else:
                _, tenant_id, matcher, url, _inc = op
                gm = None
                if matcher.type != RouteMatcherType.NORMAL:
                    gm = self._group_members(tenant_id, matcher)
                for base in targets:
                    base.patch_remove(tenant_id, matcher, url,
                                      group_members=gm)
        except PatchFallback:
            self.patch_fallbacks += 1
            return False
        self.patch_count += 1
        self.patch_host_s += time.perf_counter() - t0
        return True

    def _flush_patches(self, own_slots: int = 0) -> None:
        """Ship accumulated host patches to device as narrow scatter
        updates (coalesced: at most one flush per dispatch, however many
        mutations landed since). Functional update by default — the old
        tables stay alive for in-flight dispatches; when nothing else is
        in flight the tables are DONATED so XLA updates them in place
        with no table copy at all. ``own_slots`` is the ring slots the
        CALLER itself holds (the async leg acquires before dispatching,
        so its own not-yet-dispatched slot is counted in ``in_flight``
        but provably isn't a reader of the old tables yet)."""
        base = self._base_ct
        if not isinstance(base, PatchableTrie) or not base.dirty \
                or self._device_trie is None:
            return
        from ..ops.match import patch_device_trie
        ring = self._ring
        # donation exclusivity rides the matcher's single-serving-thread
        # contract (the same one the overlay dicts and _apply_pending_swap
        # already assume): only the serving thread flushes, always BEFORE
        # its own dispatch, and the sync/async legs both synchronize their
        # walks (incl. the escalation re-walk) without yielding between
        # slot release and expansion — so in_flight<=own_slots plus an
        # empty quarantine (timed-out/cancelled walks still reading the
        # tables park their arrays there) proves no device reader of the
        # old tables exists. Mutation-side callers never flush.
        donate = ring is None or (ring.in_flight <= own_slots
                                  and not len(ring.quarantine))
        t0 = time.perf_counter()
        dev, stats = patch_device_trie(self._device_trie, base,
                                       device=self.device, donate=donate)
        self._device_trie = dev
        dt = time.perf_counter() - t0
        self.patch_flushes += 1
        self.patch_device_s += dt
        # ISSUE 9: every flush lands in the compile ledger's patch stream
        # (reason / mutations coalesced / rows touched / bytes shipped) so
        # churn reads as narrow updates, not invisible work
        from ..obs import OBS
        OBS.profiler.ledger.record_patch(
            reason="+".join(stats["full"]) if stats["full"] else "rows",
            mutations=stats["ops"], rows=stats["rows"],
            bytes_shipped=stats["bytes"], duration_s=dt)
        if stats["reshaped"]:
            # arena growth / edge regrow changed a table shape: the walk
            # re-traces. The triggering batch inherently pays its own
            # shape's compile, but the OTHER warm shapes (pipeline
            # floors) compile on a background thread — same off-thread
            # warming a compaction install gets from the compile thread.
            threading.Thread(target=self._warm_walk, args=(base, dev),
                             name="tpu-matcher-warm", daemon=True).start()

    def _overlay_record(self, op: Tuple) -> None:
        """Fold one log op into the serving overlay (delta tries + tombstones).

        The single definition of the overlay semantics: an add supersedes any
        base copy (tombstone) and supplies the live version via the delta
        trie; a remove tombstones the base copy and retracts any delta copy.
        """
        if op[0] == "add":
            _, tenant, route = op
            self._delta.setdefault(tenant, SubscriptionTrie()).add(route)
            self._tomb.setdefault(tenant, set()).add(
                (route.matcher.mqtt_topic_filter, route.receiver_url))
        else:
            _, tenant, matcher, url, inc = op
            d = self._delta.get(tenant)
            if d is not None:
                d.remove(matcher, url, inc)
            self._tomb.setdefault(tenant, set()).add(
                (matcher.mqtt_topic_filter, url))
        self._overlay_n += 1

    # ---------------- compilation / compaction -----------------------------

    @property
    def overlay_size(self) -> int:
        return self._overlay_n

    def _replay_log_into_shadow(self) -> None:
        for op in self._log:
            apply_log_op(self._shadow, op)
        self._log.clear()

    def _compile_shadow(self) -> Tuple[CompiledTrie, object]:
        import time as _time
        t0 = _time.perf_counter()
        self.compile_count += 1
        ct = compile_tries(self._shadow, max_levels=self.max_levels,
                           probe_len=self.probe_len)
        if self._patching_enabled():
            # ISSUE 9: pad the arenas with pow2 growth headroom so the
            # serving base accepts in-place patches without reshaping
            # (the padded shape is what jit compiles against)
            ct = PatchableTrie(ct)
        from ..ops.match import DeviceTrie  # deferred: keeps jax optional
        dev = DeviceTrie.from_compiled(ct, device=self.device)
        self._warm_walk(ct, dev)
        self._last_compile_s = _time.perf_counter() - t0
        self.compile_time_s += self._last_compile_s
        return ct, dev

    def _warm_walk(self, ct: CompiledTrie, dev) -> None:
        """Pre-compile the serving walk for this table's shapes at the
        smallest serving batches: 16 (the _pow2_batch floor) and, when
        the async pipeline is on, the shallow-queue latency floor too —
        the idle-broker single-publish shape must not pay a first-use
        compile on the serving path.

        XLA re-compiles whenever the table SHAPES change, and an
        uncompiled walk on the serving path delays the first match by
        seconds — enough to expire a short-MESSAGE_EXPIRY will that fired
        right before it. Warming here (mutation-triggered background
        compile path) keeps the publish path jit-warm."""
        try:
            from ..ops.match import (Probes, walk_routes,
                                     walk_routes_donated)
            from .kernels import fused_enabled, fused_walk_routes
            from .pipeline import donation_enabled, pipeline_min_floor
            kw = dict(probe_len=ct.probe_len, k_states=self.k_states,
                      max_intervals=self.max_intervals)
            # warm exactly the (batch, kernel) pairs _walk_primary will
            # select: the sync floor always; once the async ring has
            # actually served (self._ring exists), ALSO the shallow-queue
            # latency floor and the busy-ring throughput floor on the
            # pipeline's kernel (donated lax or fused) — a live pipeline
            # must stay jit-warm across recompiles, but sync-only
            # deployments (and the test suite) never pay for shapes they
            # don't serve. The very first shallow publish of a process
            # compiles its floor lazily instead.
            if fused_enabled(dev):
                def sync_fn(d, p):
                    return fused_walk_routes(d, p, **kw)
                pipe_fn = sync_fn
            else:
                def sync_fn(d, p):
                    return walk_routes(d, p, esc_k=0, **kw)
                if donation_enabled():
                    def pipe_fn(d, p):
                        return walk_routes_donated(d, p, esc_k=0, **kw)
                else:
                    pipe_fn = sync_fn
            warm = [(16, sync_fn)]
            if self._ring is not None:
                warm += [(16, pipe_fn), (pipeline_min_floor(), pipe_fn)]
            seen = set()
            for b, fn in warm:
                if (b, fn) in seen:
                    continue
                seen.add((b, fn))
                tok = tokenize([["warm"]], [-1], max_levels=ct.max_levels,
                               salt=ct.salt, batch=b)
                res = fn(dev, Probes.from_tokenized(tok,
                                                    device=self.device))
                np.asarray(res.overflow)
            # ISSUE 10 satellite (ROADMAP PR 9 follow-up (c)): pre-warm
            # the patch-scatter jits too, so the FIRST churn flush stops
            # paying its one-off trace on the serving path. On a
            # DELAYED background thread: the walk warm gates first
            # serving and must stay inline, but churn starts long after
            # install — ~0.6s of scatter traces competing with a cold
            # process's first serves (workers hold 1s RPC deadlines
            # across them) would cost more than they save, so the warm
            # waits out the cold-start window first. Deduped per shape
            # class
            # inside warm_patch_scatter, so multi-range workers compile
            # each class once.
            from ..ops import match as _om
            if isinstance(ct, PatchableTrie) \
                    and ct.node_tab.shape[0] >= _om.WARM_SCATTER_MIN_ROWS:
                from ..utils.env import env_float
                # capture ONLY shape classes + device: closing over
                # self would pin the matcher (and its device breaker on
                # the process-global board) for the whole delay window,
                # and holding the live tables would race a donated
                # flush consuming them mid-delay
                device = self.device
                shapes = _om.scatter_warm_shapes(dev)
                scatter_warm_fn = _om.warm_patch_scatter

                def _warm_scatters():
                    try:
                        time.sleep(max(0.0, env_float(
                            "BIFROMQ_SCATTER_WARM_DELAY_S", 1.0)))
                        scatter_warm_fn(shapes, device=device)
                    except Exception:  # noqa: BLE001 — best-effort
                        pass
                t = threading.Thread(target=_warm_scatters,
                                     name="tpu-matcher-warm-scatter",
                                     daemon=True)
                self._scatter_warm_thread = t
                t.start()
        except Exception:  # noqa: BLE001 — warm-up is best-effort
            pass

    def refresh(self) -> CompiledTrie:
        """Blocking quiesce: every pending mutation lands in the base.

        ISSUE 9: when the base is patchable and every pending log op was
        already folded in as a patch (the overlay is empty), quiesce is
        just a shadow sync + device flush — NO rebuild. The full compile
        survives for cold start, overlay-resident ops, and mesh bases.
        """
        self.drain()
        if self._base_ct is None:
            self._compile_reason = "first_base"
            self._replay_log_into_shadow()
            ct, dev = self._compile_shadow()
            self._install_base(ct, dev)
        elif self._log:
            if self._overlay_n == 0 and self._base_patchable():
                # base already exact (patch-first path): sync the shadow
                # so the next compaction replays from the right snapshot
                self._replay_log_into_shadow()
            else:
                self._compile_reason = "refresh"
                self._replay_log_into_shadow()
                ct, dev = self._compile_shadow()
                self._install_base(ct, dev)
        self._flush_patches()
        return self._base_ct

    def _base_patchable(self) -> bool:
        """Is the INSTALLED base exact under the patch-first path (so a
        quiesce needs no rebuild)? The mesh subclass answers for its
        per-shard arenas."""
        return isinstance(self._base_ct, PatchableTrie)

    @staticmethod
    def _base_salt(ct) -> object:
        """Salt fingerprint of a base snapshot — works for the single-chip
        CompiledTrie and the mesh's ShardedTables (per-shard salts)."""
        salt = getattr(ct, "salt", None)
        if salt is not None:
            return salt
        shards = getattr(ct, "compiled", None)
        if shards is not None:
            return tuple(getattr(s, "salt", None) for s in shards)
        return None

    def _install_base(self, ct: CompiledTrie, dev) -> None:
        prev = self._base_ct
        self._base_ct = ct
        self._device_trie = dev
        # mutations not in this base = the log suffix. ISSUE 9: fold them
        # in as PATCHES on the fresh arenas (the patch methods are
        # find-or-append idempotent, so replaying an op that raced the
        # compile snapshot is safe); only ops the patcher declines land
        # in the overlay. Dirty rows flush on the next dispatch.
        self._delta = {}
        self._tomb = {}
        self._overlay_n = 0
        for op in self._log:
            if not self._try_patch(op):
                self._overlay_record(op)
        # ISSUE 6 satellite (PR-4 follow-up): a PURE compaction — folding
        # the overlay into a new base with the SAME salt — produces an
        # automaton equivalent to base ⊕ overlay, so every cached result
        # stays exact: mutations already invalidated their keys when they
        # were applied (add/remove_route), and in-flight puts racing a
        # mutation are defeated by the per-tenant seq. Only a SALT change
        # (hash-collision recompile) or the first install still bumps the
        # global generation; reset-from-KV rebuilds through clone_empty
        # (fresh cache) and never reaches here.
        bumped = False
        if self.match_cache is not None:
            if prev is None or self._base_salt(prev) != self._base_salt(ct):
                self.match_cache.bump_all()
                bumped = True
        self._ledger_record(ct, bumped)
        # ISSUE 12: a compiled install renumbers the arenas (even a pure
        # same-salt compaction re-runs the DFS) — the delta stream must
        # re-anchor so replicas resync instead of scattering stale rows
        _safe_hook(self.on_rebase, "rebase", self._base_salt(ct),
                   self._compile_reason)

    def _ledger_record(self, ct, bumped: bool) -> None:
        """ISSUE 8: stamp this install into the compile-event ledger so
        rebuild storms are attributable — trigger reason, compile wall
        time, salt, table bytes, the fused VMEM verdict, and whether the
        match-cache generation was bumped. The byte/VMEM derivation
        lives in one place (obs.capacity.record_compile_event — bench
        builds stamp through it too)."""
        from ..obs.capacity import record_compile_event
        record_compile_event(ct, reason=self._compile_reason,
                             duration_s=self._last_compile_s,
                             salt=self._base_salt(ct),
                             generation_bumped=bumped)

    def _patch_frag_pending(self) -> bool:
        """ISSUE 9 compaction trigger: dead+garbage slots crossed the
        tombstone threshold. Steady patching churn below it (and ANY
        volume of pure adds, which never fragment) compacts never."""
        base = self._base_ct
        return isinstance(base, PatchableTrie) and base.frag_pending()

    def _maybe_compact(self, force: bool = False) -> None:
        # trigger on the FIRST mutation too (base is None): the first base
        # builds in the background so the first publish finds trie tables
        # AND the walk jit already warm, instead of paying both compiles
        # inline (the reference's refresh-on-mutation contract,
        # TenantRouteCache.java:100). ``force`` recompiles regardless of
        # overlay size (shard re-placement: new pins need a new build).
        # ISSUE 9: with patch-first mutations the overlay stays empty and
        # the threshold trigger goes quiet; compaction becomes the
        # FRAGMENTATION fallback (tombstone/garbage ratio) instead of the
        # every-2048-mutations rebuild.
        frag = self.auto_compact and self._patch_frag_pending()
        if (self._compact_thread is not None
                or (not force and not frag
                    and (not self.auto_compact
                         or (self._base_ct is not None
                             and self._overlay_n < self.compact_threshold)))):
            self._apply_pending_swap()
            return
        # ledger attribution (ISSUE 8): why this build is happening
        if self._base_ct is None:
            self._compile_reason = "first_base"
        elif force:
            self._compile_reason = "forced"
        elif self._overlay_n >= self.compact_threshold:
            self._compile_reason = "threshold"
        else:
            self._compile_reason = "frag"
        # snapshot: fold the log into the shadow NOW (serving thread, cheap —
        # O(log)); the compile thread then reads only the frozen shadow
        self._replay_log_into_shadow()

        def work():
            try:
                result = self._compile_shadow()
            except Exception:  # noqa: BLE001 — must not wedge compaction
                import logging
                logging.getLogger(__name__).exception(
                    "background compaction failed; will retry")
                result = None
            with self._swap_lock:
                self._pending_swap = result
                self._compact_done = True

        self._compact_done = False
        t = threading.Thread(target=work, name="tpu-matcher-compact",
                             daemon=True)
        self._compact_thread = t
        t.start()

    def _apply_pending_swap(self) -> None:
        with self._swap_lock:
            pending, self._pending_swap = self._pending_swap, None
            done = self._compact_done
        if pending is not None:
            self._install_base(*pending)
        if done:
            # thread finished (successfully or not): allow the next compact
            self._compact_thread = None
            self._compact_done = False

    def drain(self) -> None:
        """Wait for any in-flight compaction and apply its result."""
        t = self._compact_thread
        if t is not None:
            t.join()
        self._apply_pending_swap()

    @property
    def compiled(self) -> CompiledTrie:
        return self.refresh()

    @property
    def device_trie(self):
        self.refresh()
        return self._device_trie

    # ---------------- query side (≈ SubscriptionCache.get) -----------------

    def match_batch(self, queries: Sequence[Tuple[str, Sequence[str]]],
                    *, max_persistent_fanout: int = UNCAPPED_FANOUT,
                    max_group_fanout: int = UNCAPPED_FANOUT,
                    batch: Optional[int] = None,
                    stats: Optional[dict] = None,
                    **device_kw) -> List[MatchedRoutes]:
        """The cache-plane front-end (ISSUE 4, ≈ SubscriptionCache.get →
        TenantRouteCache): per-query cache probe, then in-batch dedup so N
        identical (tenant, topic) rows walk ONCE — only the unique misses
        reach ``_match_batch_device``, so hits also shrink the padded
        device batch. Cached/fanned-out results are shared objects and
        must be treated read-only by callers (the established contract of
        the dist pub cache)."""
        if not queries:
            return []
        cache = self.match_cache
        if cache is None:
            return self._match_batch_device(
                queries, max_persistent_fanout=max_persistent_fanout,
                max_group_fanout=max_group_fanout, batch=batch,
                stats=stats, **device_kw)
        # fold any finished background compaction in BEFORE probing: its
        # generation bump must land before this batch's token snapshots,
        # not mid-walk (which would refuse every put of the batch)
        self._apply_pending_swap()
        caps = (max_persistent_fanout, max_group_fanout)
        out, uniq, uniq_queries, miss_rows, tokens = \
            self._frontend_probe(queries, caps)
        if uniq_queries:
            res = self._match_batch_device(
                uniq_queries, max_persistent_fanout=max_persistent_fanout,
                max_group_fanout=max_group_fanout, batch=batch,
                stats=stats, **device_kw)
            self._frontend_fill(out, res, uniq, miss_rows, tokens, caps)
        self._frontend_metrics(len(queries), uniq_queries, miss_rows)
        return out

    def _frontend_probe(self, queries, caps):
        """Cache probe + in-batch dedup (the ISSUE 4 front-end, shared by
        the sync and async serving paths): returns (out, uniq, uniq_queries,
        miss_rows, tokens) where ``out`` holds the hits and ``tokens`` the
        pre-match invalidation snapshots — taken BEFORE any walk is issued,
        so a mutation landing mid-match (the async path genuinely awaits
        across the event loop) defeats the store."""
        cache = self.match_cache
        out: List[Optional[MatchedRoutes]] = [None] * len(queries)
        uniq: Dict[Tuple[str, Tuple[str, ...]], int] = {}
        uniq_queries: List[Tuple[str, Sequence[str]]] = []
        miss_rows: List[Tuple[int, int]] = []   # (query idx, unique pos)
        for qi, (tenant_id, levels) in enumerate(queries):
            key = _query_key(levels)
            m = cache.get(tenant_id, key, caps)
            if m is not None:
                out[qi] = m
                continue
            uk = (tenant_id, key)
            pos = uniq.get(uk)
            if pos is None:
                pos = uniq[uk] = len(uniq_queries)
                uniq_queries.append((tenant_id, levels))
            miss_rows.append((qi, pos))
        tokens = ({t: cache.token(t) for t in {q[0] for q in uniq_queries}}
                  if uniq_queries else {})
        return out, uniq, uniq_queries, miss_rows, tokens

    def _frontend_fill(self, out, res, uniq, miss_rows, tokens, caps):
        cache = self.match_cache
        for (tenant_id, key), pos in uniq.items():
            cache.put(tenant_id, key, caps, res[pos], tokens[tenant_id])
        for qi, pos in miss_rows:
            out[qi] = res[pos]

    def _frontend_metrics(self, n_queries, uniq_queries, miss_rows):
        # global section totals: ONE locked inc per batch, not per row.
        # Per-tenant OBS hit rates are fed by the PUB plane alone
        # (dist/service.py) — recording both planes into one window made
        # the /tenants number interpretable as neither.
        from ..utils.metrics import MATCH_CACHE
        MATCH_CACHE.inc(self.match_cache.scope, "hits",
                        n_queries - len(miss_rows))
        MATCH_CACHE.inc(self.match_cache.scope, "misses", len(miss_rows))
        if uniq_queries:
            MATCH_CACHE.record_dedup(len(uniq_queries),
                                     len(miss_rows) - len(uniq_queries))
        # ISSUE 8: the profiler's cache-bypass / dedup-savings counters
        # (rows that never reached the device) — three int adds
        from ..obs import OBS
        OBS.profiler.record_frontend(
            n_queries, n_queries - len(miss_rows),
            len(miss_rows) - len(uniq_queries))

    # ---------------- async device pipeline (ISSUE 6 tentpole) -------------

    def _pipeline_ring(self):
        if self._ring is None:
            from .pipeline import DispatchRing
            self._ring = DispatchRing()
            from ..obs import OBS
            OBS.device.register_ring(self._ring)
        return self._ring

    async def drain_device(self, timeout_s: Optional[float] = None) -> bool:
        """Graceful drain (ISSUE 7): wait bounded for in-flight device
        batches to retire, then sweep the quarantine. Shutdown and
        compaction call this so a slot mid-walk finishes (or is given up
        on) instead of being torn down under the device. Returns whether
        the ring actually went idle."""
        ring = self._ring
        if ring is None:
            return True
        from ..resilience.device import drain_timeout_s
        if timeout_s is None:
            timeout_s = drain_timeout_s()
        idle = await ring.wait_idle(timeout_s)
        ring.quarantine.sweep()
        return idle

    async def match_batch_async(self, queries, *,
                                max_persistent_fanout: int = UNCAPPED_FANOUT,
                                max_group_fanout: int = UNCAPPED_FANOUT,
                                batch: Optional[int] = None,
                                stats: Optional[dict] = None,
                                **device_kw) -> List[MatchedRoutes]:
        """Pipelined serving path: same results as ``match_batch``, but
        the device walk is dispatched through the bounded in-flight ring
        and awaited on READINESS — batch N+1 tokenizes and enqueues while
        batch N is still walking, and the event loop keeps serving between
        readiness polls instead of blocking inside ``device_get``.

        ``stats`` (optional dict) receives ``device_s``: THIS batch's own
        match cost — cache probe + dispatch+ready+fetch + host expansion
        and cache fill, i.e. the same work the sync path's wall clock
        covers, minus only the ring-acquire wait. Callers attributing
        device cost (the dist worker's per-tenant SLO shares) must use it
        instead of their outer wall clock, which under an overlapped
        pipeline also counts that wait and concurrent batches' work —
        and with it, toggling ``BIFROMQ_PIPELINE`` does not shift what
        the "device" stage histograms measure. ``stats["degraded"]``
        carries the reason when the batch was served from the host
        oracle (ISSUE 7: breaker open, watchdog timeout, device error)
        so the worker can emit MATCH_DEGRADED events without a raising
        boundary.

        Degrades to the sync path when the pipeline is disabled
        (``BIFROMQ_PIPELINE=0``) or the subclass replaced the device plane
        (``supports_async = False``).
        """
        from .pipeline import pipeline_enabled
        if not queries:
            return []
        if not (self.supports_async and pipeline_enabled()):
            return self.match_batch(
                queries, max_persistent_fanout=max_persistent_fanout,
                max_group_fanout=max_group_fanout, batch=batch,
                stats=stats, **device_kw)
        if device_kw:
            # the sync path would TypeError on unknown kwargs inside
            # _match_batch_device; an env flag must not turn that into a
            # silent drop
            raise TypeError("match_batch_async got unsupported kwargs: "
                            f"{sorted(device_kw)}")
        caps = (max_persistent_fanout, max_group_fanout)
        cache = self.match_cache
        t_front = time.perf_counter()
        if cache is not None:
            self._apply_pending_swap()
            out, uniq, uniq_queries, miss_rows, tokens = \
                self._frontend_probe(queries, caps)
        else:
            out = [None] * len(queries)
            uniq_queries = list(queries)
        front_s = time.perf_counter() - t_front
        if stats is not None:
            # all-hit batches: the cache probe IS the whole match cost
            stats["device_s"] = front_s
        if uniq_queries:
            t_disp = time.perf_counter()
            res, degraded, acquire_s = await self._device_serve_async(
                uniq_queries, batch, max_persistent_fanout,
                max_group_fanout)
            if cache is not None:
                self._frontend_fill(out, res, uniq, miss_rows, tokens,
                                    caps)
            else:
                out = res
            if stats is not None:
                # probe + this batch's dispatch→expand→fill: everything
                # the sync wall clock covers except the ring-acquire wait
                # (queue time under a saturated pipeline, not match cost —
                # folding it in would inflate the "device" stage and the
                # per-tenant attribution feeding the noisy detector)
                stats["device_s"] = front_s + (
                    time.perf_counter() - t_disp - acquire_s)
                if degraded is not None:
                    stats["degraded"] = degraded
        if cache is not None:
            self._frontend_metrics(len(queries), uniq_queries, miss_rows)
        return out

    async def _device_serve_async(self, uniq_queries, batch,
                                  max_persistent_fanout, max_group_fanout):
        """The failure-bounded device leg of the async path (ISSUE 7).

        Returns ``(results, degraded_reason, acquire_s)`` —
        ``degraded_reason`` is None when the device served, else one of
        ``breaker`` (circuit open: dispatch skipped entirely), ``timeout``
        (watchdog fired: the ring slot was reclaimed, the orphaned arrays
        quarantined), or ``device_error`` (dispatch/fetch raised);
        ``acquire_s`` is the ring-acquire wait the caller subtracts from
        its device-time accounting. Every degraded serve comes from
        ``match_from_tries`` — the authoritative host oracle, exact by
        construction — so the publish path NEVER fails on a sick device;
        it just loses the accelerator speedup until the canary re-closes
        the breaker."""
        from ..resilience.device import DeviceTimeoutError
        from ..utils.metrics import FABRIC, FabricMetric
        br = self.device_breaker
        verdict = br.admit() if br is not None else "ok"
        reason = None
        oracle_rows = None
        timing = {"acquire_s": 0.0}
        if verdict == "rejected":
            reason = "breaker"
        else:
            settled = False
            try:
                res = await self._device_leg_async(
                    uniq_queries, batch, max_persistent_fanout,
                    max_group_fanout, timing)
                if br is not None:
                    if verdict == "canary":
                        ok, oracle_rows = self._canary_parity(
                            uniq_queries, res, max_persistent_fanout,
                            max_group_fanout)
                        if ok:
                            br.record_success()
                        else:
                            br.record_failure("canary row parity")
                            reason = "canary_parity"
                    elif br.state == "closed":
                        # an "ok"-admitted batch completing while the
                        # breaker is no longer closed is a pre-trip
                        # STRAGGLER: its success must not close the
                        # circuit past the canary parity bar (not even
                        # indirectly, by landing while a canary is out)
                        br.record_success()
                settled = True
                if reason is None:
                    return res, None, timing["acquire_s"]
            except DeviceTimeoutError as e:
                FABRIC.inc(FabricMetric.DEVICE_TIMEOUT)
                if br is not None:
                    br.record_failure(repr(e))
                    settled = True
                reason = "timeout"
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — degrade, don't fail
                if br is not None:
                    br.record_failure(repr(e))
                    settled = True
                logging.getLogger(__name__).warning(
                    "device match failed; serving host oracle: %r", e)
                reason = "device_error"
            finally:
                if br is not None and verdict == "canary" and not settled:
                    # cancelled mid-probe with no verdict: the half-open
                    # budget must not leak or the breaker wedges refusing
                    br.release_probe()
        FABRIC.inc(FabricMetric.MATCH_DEGRADED, len(uniq_queries))
        from ..obs import OBS
        OBS.profiler.record_batch(
            n_queries=len(uniq_queries), batch=len(uniq_queries),
            kernel="oracle", dispatch_s=0.0, path="async",
            degraded=reason)
        with trace.span("match.degraded", reason=reason,
                        n_queries=len(uniq_queries)):
            if oracle_rows is None:
                # parity failures already walked the oracle — reuse it
                oracle_rows = self.match_from_tries(
                    uniq_queries,
                    max_persistent_fanout=max_persistent_fanout,
                    max_group_fanout=max_group_fanout)
            return oracle_rows, reason, timing["acquire_s"]

    async def _device_leg_async(self, uniq_queries, batch,
                                max_persistent_fanout, max_group_fanout,
                                timing=None):
        """dispatch → fetch-on-ready → expand through the bounded ring,
        with the ISSUE 7 watchdog armed on the readiness wait. A timeout
        RECLAIMS the slot: the ring releases it immediately (the next
        batch keeps flowing) and the orphaned result arrays — which may
        alias donated probe buffers the device is still writing — go to
        quarantine until actually ready. ``timing["acquire_s"]`` reports
        the ring-acquire wait (queue time, not match cost) even when the
        leg later raises."""
        from ..resilience.device import DeviceTimeoutError
        from .pipeline import donation_enabled
        ring = self._pipeline_ring()
        # ISSUE 11 overlap: stage-1 prep (tokenize + probe upload) runs
        # BEFORE ring admission — batch N+1 tokenizes while batch N is
        # still walking, and a full ring stalls only the enqueue, not
        # the byte plane. Prep TICKETS (depth + 1) bound the probe
        # batches resident on device: parked callers beyond one
        # prep-ahead wait un-uploaded, keeping the capacity model's
        # in-flight byte accounting honest. The dispatch half re-preps
        # iff a compaction swapped the base during the admission wait.
        t_acq = time.perf_counter()
        await ring.acquire_prep()
        try:
            if batch is None:
                # queue-depth-adaptive pow2 floor: idle ring ⇒ small pad
                # to cut time-to-first-result, busy ring ⇒ the
                # throughput floor. Read before slot admission
                # (planned_floor = the pre-acquire twin).
                batch = _pow2_batch(len(uniq_queries),
                                    floor=ring.planned_floor())
            prep = self._prepare_probes(uniq_queries, batch)
            await ring.acquire()
            if timing is not None:
                # queue time: prep-ticket wait + slot wait, minus the
                # prep work itself (match cost, attributed via the
                # tokenize stage)
                timing["acquire_s"] = max(
                    0.0, time.perf_counter() - t_acq - prep.tokenize_s)
            try:
                fl = self._dispatch_prepared(prep,
                                             donate=donation_enabled(),
                                             watchdogged=True)
                ring.start_fetch(fl.res)
                t0 = time.perf_counter()
                try:
                    with trace.span("device.ready", batch=fl.batch,
                                    kernel=fl.kernel):
                        await self._await_ready(ring, fl)
                except DeviceTimeoutError:
                    ring.reclaim(fl.res,
                                 tag=getattr(fl, "quarantine_tag", None))
                    # ISSUE 15: let the subclass attribute the timeout
                    # (the mesh feeds the implicated SHARD's breaker)
                    self._note_device_timeout(fl)
                    # ISSUE 20: the e2e plane's degraded map names the
                    # component stalling deliveries (the mesh hook above
                    # already named individual shards; this covers the
                    # single-chip matcher)
                    from ..obs import OBS
                    OBS.e2e.set_degraded(
                        getattr(fl, "quarantine_tag", None) or "device",
                        "device_timeout")
                    raise
                except BaseException:
                    # cancelled mid-wait (caller timeout, client
                    # disconnect): the arrays may still be in flight and
                    # may alias donated probe buffers — park them like a
                    # timeout does, minus the timeout accounting, or
                    # dropping the last reference here would be the
                    # exact use-after-donate the quarantine exists to
                    # prevent
                    ring.quarantine.add(fl.res,
                                        tag=getattr(fl, "quarantine_tag",
                                                    None))
                    raise
                ready_s = time.perf_counter() - t0
                STAGES.record("device.ready", ready_s)
                # a step that completes clears the single-chip degraded
                # mark (per-shard marks clear on their own ready rows)
                from ..obs import OBS as _obs
                _obs.e2e.clear_degraded("device")
            finally:
                ring.release()
        finally:
            # held for the WHOLE slot tenure: tickets bound prepped +
            # in-flight batches together at depth+1, so at most ONE
            # uploaded-but-undispatched probe set exists when the ring
            # is full — the exact +1 the capacity model counts
            ring.release_prep()
        t0 = time.perf_counter()
        with trace.span("device.fetch"):
            overflow, starts_a, counts_a = self._fetch_walk(fl.res)
        fetch_s = time.perf_counter() - t0
        STAGES.record("device.fetch", fetch_s)
        t0 = time.perf_counter()
        out = self._expand_walk(fl, overflow, starts_a, counts_a,
                                max_persistent_fanout, max_group_fanout)
        # ISSUE 8: the continuous profiler's per-batch stage record —
        # attribute increments + one ring store, nothing else
        from ..obs import OBS
        OBS.profiler.record_batch(
            n_queries=len(fl.queries), batch=fl.batch, kernel=fl.kernel,
            tokenize_s=fl.tokenize_s, dispatch_s=fl.dispatch_s,
            ready_s=ready_s, fetch_s=fetch_s,
            expand_s=time.perf_counter() - t0,
            dev_expand_s=fl.dev_expand_s, path="async")
        return out

    async def _await_ready(self, ring, fl) -> None:
        """Readiness-wait hook (ISSUE 16): one watchdogged wait over the
        whole in-flight batch. The mesh overrides this for SPLIT
        dispatches — per-fault-domain groups each wait under their own
        per-shard deadline so a hang indicts one device, not the step."""
        await ring.wait_ready(fl.res, fault=fl.fault)

    def _note_device_timeout(self, fl) -> None:
        """Subclass hook (ISSUE 15): attribute a watchdog timeout of one
        in-flight batch — the mesh feeds the implicated shard breaker(s)
        and settles outstanding canary probes. The single-chip matcher's
        own breaker is fed by the caller, so this is a no-op here."""

    def _canary_parity(self, queries, device_rows,
                       max_persistent_fanout, max_group_fanout):
        """Half-open success bar: the canary batch's device rows must be
        row-identical to the host oracle (receivers + groups per row) —
        a device that returns plausible-but-wrong rows after a fault must
        NOT re-close the breaker. Returns ``(ok, oracle_rows)`` so a
        failed parity check can serve the already-computed oracle rows
        instead of walking the host tries a second time."""
        oracle = self.match_from_tries(
            queries, max_persistent_fanout=max_persistent_fanout,
            max_group_fanout=max_group_fanout)

        def canon(m):
            return (sorted((r.matcher.mqtt_topic_filter, r.receiver_url)
                           for r in m.normal),
                    {f: sorted(r.receiver_url for r in ms)
                     for f, ms in m.groups.items()})
        return all(canon(d) == canon(o)
                   for d, o in zip(device_rows, oracle)), oracle

    def _match_batch_device(self, queries: Sequence[Tuple[str,
                                                          Sequence[str]]],
                            *, max_persistent_fanout: int = UNCAPPED_FANOUT,
                            max_group_fanout: int = UNCAPPED_FANOUT,
                            batch: Optional[int] = None,
                            stats: Optional[dict] = None
                            ) -> List[MatchedRoutes]:
        """Match (tenant_id, topic_levels) pairs; returns per-query routes.

        Exact at every instant: base walk ⊕ overlay ⊖ tombstones equals a
        match against the authoritative tries.

        The device emits matched-slot INTERVALS (ops.match.walk_routes, the
        compressed MatchedRoutes form); the host expands all rows with one
        vectorized ragged-arange (ops.match.expand_intervals) — never a
        per-slot Python loop (the c4 92-filters/s failure mode, VERDICT
        r4 #2). This sync entry is dispatch+fetch+expand back to back; the
        async pipeline (match_batch_async) runs the same three stages with
        an is_ready await between dispatch and fetch.

        ISSUE 7: the device breaker gates this sync leg too — open
        serves the host oracle with no dispatch, a device error feeds
        the breaker and then PROPAGATES (the worker's degradation
        boundary owns the sync fallback), and a half-open admission
        holds the canary batch to oracle row parity.

        ISSUE 11 (the PR 7 carry-over): the fetch is no longer a
        blocking synchronize the watchdog cannot preempt — it waits on
        the same ``is_ready`` short-poll the async leg uses, honoring
        ``BIFROMQ_DEVICE_DEADLINE_S``, and a truly hung device degrades
        THIS caller to the exact host oracle (breaker fed, MATCH_DEGRADED
        counted) instead of wedging it forever.
        """
        if not queries:
            return []
        from ..resilience.device import DeviceTimeoutError
        br = self.device_breaker
        verdict = br.admit() if br is not None else "ok"
        if verdict == "rejected":
            from ..utils.metrics import FABRIC, FabricMetric
            FABRIC.inc(FabricMetric.MATCH_DEGRADED, len(queries))
            if stats is not None:
                # the sync serve has no raising boundary here — the
                # worker's MATCH_DEGRADED event outlet keys on this
                stats["degraded"] = "breaker"
            with trace.span("match.degraded", reason="breaker",
                            n_queries=len(queries)):
                return self.match_from_tries(
                    queries, max_persistent_fanout=max_persistent_fanout,
                    max_group_fanout=max_group_fanout)
        try:
            fl = self._dispatch_device(queries, batch)
            t0 = time.perf_counter()
            with trace.span("device.fetch"):
                self._await_ready_sync(fl.res)
                overflow, starts_a, counts_a = self._fetch_walk(fl.res)
            fetch_s = time.perf_counter() - t0
            STAGES.record("device.fetch", fetch_s)
            t0 = time.perf_counter()
            out = self._expand_walk(fl, overflow, starts_a, counts_a,
                                    max_persistent_fanout,
                                    max_group_fanout)
            from ..obs import OBS
            OBS.profiler.record_batch(
                n_queries=len(fl.queries), batch=fl.batch,
                kernel=fl.kernel, tokenize_s=fl.tokenize_s,
                dispatch_s=fl.dispatch_s,
                fetch_s=fetch_s, expand_s=time.perf_counter() - t0,
                dev_expand_s=fl.dev_expand_s, path="sync")
        except DeviceTimeoutError as e:
            # the watchdog fired on the SYNC leg: reclaimed slot
            # semantics without a ring — the orphaned (non-donated)
            # result arrays are dropped to the backend, the breaker is
            # fed, and this caller serves the exact host oracle
            from ..obs import OBS
            from ..utils.metrics import FABRIC, FabricMetric
            FABRIC.inc(FabricMetric.DEVICE_TIMEOUT)
            FABRIC.inc(FabricMetric.MATCH_DEGRADED, len(queries))
            if br is not None:
                br.record_failure(repr(e))
            self._note_device_timeout(fl)
            if stats is not None:
                stats["degraded"] = "timeout"
            OBS.profiler.record_batch(
                n_queries=len(queries), batch=len(queries),
                kernel="oracle", dispatch_s=0.0, path="sync",
                degraded="timeout")
            with trace.span("match.degraded", reason="timeout",
                            n_queries=len(queries)):
                return self.match_from_tries(
                    queries, max_persistent_fanout=max_persistent_fanout,
                    max_group_fanout=max_group_fanout)
        except BaseException as e:
            if br is not None:
                if isinstance(e, Exception):
                    br.record_failure(repr(e))
                elif verdict == "canary":
                    br.release_probe()
            raise
        if br is not None:
            if verdict == "canary":
                ok, oracle_rows = self._canary_parity(
                    queries, out, max_persistent_fanout, max_group_fanout)
                if not ok:
                    br.record_failure("canary row parity")
                    from ..utils.metrics import FABRIC, FabricMetric
                    FABRIC.inc(FabricMetric.MATCH_DEGRADED, len(queries))
                    if stats is not None:
                        stats["degraded"] = "canary_parity"
                    with trace.span("match.degraded",
                                    reason="canary_parity",
                                    n_queries=len(queries)):
                        return oracle_rows
                br.record_success()
            elif br.state == "closed":
                # pre-trip straggler guard, same as the async leg
                br.record_success()
        return out

    def _prepare_probes(self, queries, batch: Optional[int] = None,
                        ) -> _Prepared:
        """Stage 0 (ISSUE 11, the ``tokenize`` stage): byte-plane topic
        prep + probe upload, SEPARATE from the walk enqueue so the async
        leg runs it before ring admission — batch N+1 tokenizes while
        batch N is still walking — and the profiler attributes prep
        apart from dispatch.

        String/bytes topic rows (the serving call sites hand raw topics
        now) pack into ONE contiguous ``TopicBytes`` buffer; with
        ``BIFROMQ_DEVICE_TOKENIZE`` on, the raw bytes ship to the device
        hash kernel and only bytes cross the tunnel. Pre-parsed level
        lists (legacy callers, tests) keep the token-cache host path.
        """
        from ..ops.match import Probes
        self._apply_pending_swap()
        if self._base_ct is None:
            self.refresh()
        ct = self._base_ct
        if batch is None:
            batch = _pow2_batch(len(queries))
        roots = [ct.root_of(t) for t, _ in queries]
        t0 = time.perf_counter()
        with trace.span("device.tokenize", batch=batch,
                        queries=len(queries)):
            topics = [levels for _, levels in queries]
            byte_rows = all(isinstance(t, (str, bytes)) for t in topics)
            tok = probes = None
            if byte_rows:
                from ..models.bytetok import TopicBytes
                from ..ops.tokenize import (device_tokenize,
                                            device_tokenize_enabled)
                tb = TopicBytes.from_topics(topics)
                if device_tokenize_enabled():
                    tok, probes = device_tokenize(
                        tb, roots, max_levels=ct.max_levels,
                        salt=ct.salt, batch=batch, device=self.device)
                else:
                    tok = tokenize(tb, roots, max_levels=ct.max_levels,
                                   salt=ct.salt, batch=batch,
                                   cache=self._tok_cache)
            else:
                tok = tokenize(topics, roots, max_levels=ct.max_levels,
                               salt=ct.salt, batch=batch,
                               cache=self._tok_cache)
            if probes is None:
                probes = Probes.from_tokenized(tok, device=self.device)
        tokenize_s = time.perf_counter() - t0
        STAGES.record("tokenize", tokenize_s)
        return _Prepared(queries=list(queries), ct=ct, tok=tok,
                         probes=probes, roots=roots, batch=batch,
                         tokenize_s=tokenize_s)

    def _dispatch_device(self, queries, batch: Optional[int] = None, *,
                         donate: bool = False,
                         watchdogged: bool = False) -> _InFlight:
        """Stage 0+1 back to back (the sync leg; the async leg preps
        before ring admission and calls ``_dispatch_prepared`` itself)."""
        return self._dispatch_prepared(self._prepare_probes(queries, batch),
                                       donate=donate,
                                       watchdogged=watchdogged)

    def _dispatch_prepared(self, prep: _Prepared, *, donate: bool = False,
                           watchdogged: bool = False) -> _InFlight:
        """Stage 1: enqueue the device walk for a prepared probe batch.

        Returns as soon as the walk is ENQUEUED (walk_routes returns on
        enqueue; only a readback synchronizes — block_until_ready is a
        no-op on the axon tunnel backend). ``donate=True`` routes through
        the donated jit so XLA reuses the probe buffers for the results
        (the pipeline's in-flight memory bound); callers must then treat
        the device probes as consumed — everything downstream here reads
        only the HOST token mirror.
        """
        from ..resilience.faults import get_injector
        # ISSUE 7 device-fault hook: error rules raise here; readiness-
        # shaping rules (hang/slow/flaky_ready) ride the _InFlight into
        # wait_ready — but ONLY the watchdogged async leg has a readiness
        # poll to thread them into. The sync leg's fetch now short-polls
        # too (ISSUE 11), but hang/slow injection stays an async-leg
        # surface. One attribute check when the injector is disabled.
        if watchdogged:
            fault = get_injector().device_rule("dispatch")
        else:
            get_injector().check_raise("device", "tpu-device", "dispatch")
            fault = None
        if self._base_ct is not prep.ct:
            # a compaction swap landed between prep and dispatch (the
            # async leg awaits ring admission in the gap): roots/salt are
            # per-snapshot, so re-prep against the installed base —
            # rare enough that the re-tokenize is noise
            prep = self._prepare_probes(prep.queries, prep.batch)
        # ISSUE 9: ship any host patches accumulated since the last
        # dispatch (one coalesced narrow update, so this batch walks the
        # post-mutation tables). watchdogged == the async leg, which
        # already holds its own (not-yet-dispatched) ring slot.
        self._flush_patches(own_slots=1 if watchdogged else 0)
        ct, tok, roots, batch = prep.ct, prep.tok, prep.roots, prep.batch
        # esc_k=0: escalation stays a SEPARATE lazily-compiled dispatch
        # (_expand_walk) — fusing it into this jit would compile the
        # high-K escalation walk on the first serving query, doubling
        # cold-start latency for a pass that almost never runs
        t0 = time.perf_counter()
        with trace.span("device.dispatch", batch=batch,
                        queries=len(prep.queries)) as sp:
            res, kernel = self._walk_primary(prep.probes, ct,
                                             donate=donate)
            if sp is not trace.NOOP:
                sp.set_tag("kernel", kernel)
        # ISSUE 6: the `device.sync` stage of the sync era is replaced by
        # the dispatch/ready/fetch split in the always-on stage
        # histograms (/metrics "stages" + the bench breakdown)
        dispatch_s = time.perf_counter() - t0
        STAGES.record("device.dispatch", dispatch_s)
        # ISSUE 19: the second device stage — fan-out expansion + peer
        # bucketing enqueued right behind the walk, so the host fetch
        # reads pre-bucketed (slot, row) pairs instead of interval grids
        dev_expand_s = 0.0
        peer_tab = None
        from ..ops.match import device_expand_enabled
        import jax
        # real device arrays only: tests (and degraded backends) hand
        # duck-typed result leaves the expansion jit cannot consume —
        # those batches keep the host expander
        if device_expand_enabled() and isinstance(res.start, jax.Array):
            from ..ops.match import expand_cap_lanes, expand_routes
            t0 = time.perf_counter()
            with trace.span("device.expand", batch=batch):
                peer_tab, slot_peer = self._peer_table(ct)
                res = expand_routes(
                    res, slot_peer, cap=batch * expand_cap_lanes(),
                    n_peers=peer_tab.n_peers)
            dev_expand_s = time.perf_counter() - t0
            STAGES.record("device.expand", dev_expand_s)
        return _InFlight(queries=prep.queries, ct=ct,
                         dev=self._device_trie, tok=tok, roots=roots,
                         res=res, tomb=self._tomb, delta=self._delta,
                         batch=batch, kernel=kernel, fault=fault,
                         dispatch_s=dispatch_s,
                         tokenize_s=prep.tokenize_s,
                         dev_expand_s=dev_expand_s, peer_tab=peer_tab)

    def _walk_primary(self, probes, ct, *, donate: bool):
        """The primary serving walk: fused Pallas kernel when enabled
        (models/kernels.py gates on env + backend + VMEM fit), else the
        lax walk — donated variant when the pipeline asked for it."""
        from .kernels import fused_enabled, fused_walk_routes
        dev = self._device_trie
        if fused_enabled(dev):
            return fused_walk_routes(
                dev, probes, probe_len=ct.probe_len,
                k_states=self.k_states,
                max_intervals=self.max_intervals), "fused"
        from ..ops.match import walk_routes, walk_routes_donated
        fn = walk_routes_donated if donate else walk_routes
        return fn(dev, probes, probe_len=ct.probe_len,
                  k_states=self.k_states,
                  max_intervals=self.max_intervals,
                  esc_k=0), ("lax_donated" if donate else "lax")

    def _peer_table(self, ct):
        """The slot→delivery-peer table for this base snapshot, host +
        device halves, cached on snapshot identity (see __init__ note on
        why patch flushes must NOT invalidate it)."""
        cached = self._peer_cache
        if cached is not None and cached[0] is ct:
            return cached[1], cached[2]
        import jax
        from ..dist.deliverer import build_peer_table
        tab = build_peer_table(ct.matchings_arr)
        dev_tab = jax.device_put(tab.slot_peer, self.device)
        self._peer_cache = (ct, tab, dev_tab)
        return tab, dev_tab

    @staticmethod
    def _await_ready_sync(res, deadline_s: Optional[float] = None,
                          spin_polls: int = 50,
                          poll_s: float = 0.0005) -> None:
        """ISSUE 11 (PR 7 carry-over): the sync leg's pre-fetch
        readiness wait — the same two-phase ``is_ready`` short-poll the
        async watchdog uses (spin for sub-ms completions, timed sleeps
        for tunnel-RTT ones), minus the event loop. Past the
        ``BIFROMQ_DEVICE_DEADLINE_S`` deadline a
        :class:`DeviceTimeoutError` fires so a hung device degrades the
        SYNC caller to the oracle instead of wedging it inside an
        uninterruptible PJRT synchronize. Backends whose arrays lack
        ``is_ready`` fall through to the blocking fetch — still correct,
        just unpreemptable (the pre-ISSUE-11 behavior)."""
        from ..resilience.device import DeviceTimeoutError, \
            device_deadline_s
        if deadline_s is None:
            deadline_s = device_deadline_s()
        ready = getattr(res, "ready_leaves", None)
        leaves = ready() if ready is not None \
            else (res.start, res.count, res.overflow)
        t0 = time.monotonic()
        polls = 0
        while True:
            try:
                if all(leaf.is_ready() for leaf in leaves):
                    return
            except AttributeError:
                return
            if (deadline_s is not None
                    and time.monotonic() - t0 >= deadline_s):
                raise DeviceTimeoutError(deadline_s)
            if polls >= spin_polls:
                time.sleep(poll_s)
            polls += 1

    @staticmethod
    def _fetch_walk(res):
        """Stage 2: the one true synchronization — writable host copies
        (escalation patches rescued rows in place; a bare asarray view of
        a jax buffer is read-only). ISSUE 7: the fetch-side device-fault
        hook fires here (error rules only — a readback can crash, it
        cannot hang-inject).

        ISSUE 19 device-expand batches read the COMPACT pair buffers —
        the interval grids stay on device (escalation/truncation rows
        fetch them lazily via _fetch_escalation_grids on the slow path).
        Returns (overflow, _HostPairs, None) in that mode; the legacy
        (overflow, starts, counts) grids otherwise."""
        from ..resilience.faults import get_injector
        get_injector().check_raise("device", "tpu-device", "fetch")
        overflow = np.array(res.overflow)
        if hasattr(res, "slots"):
            pairs = _HostPairs(
                slots=np.asarray(res.slots), rows=np.asarray(res.rows),
                row_offsets=np.asarray(res.row_offsets),
                n_pairs=int(np.asarray(res.n_pairs)),
                trunc=np.asarray(res.trunc),
                peer_slots=np.asarray(res.peer_slots),
                peer_rows=np.asarray(res.peer_rows),
                peer_offsets=np.asarray(res.peer_offsets), res=res)
            return overflow, pairs, None
        starts_a = np.array(res.start)
        counts_a = np.array(res.count)
        return overflow, starts_a, counts_a

    @staticmethod
    def _fetch_escalation_grids(res):
        """Slow-path grid readback: with device expansion on, only
        buffer-truncated rows ever need the interval grids on host — a
        deliberate synchronization OFF the serving fast path."""
        return np.asarray(res.start), np.asarray(res.count)

    def _expand_walk(self, fl: _InFlight, overflow, starts_a, counts_a,
                     max_persistent_fanout: int,
                     max_group_fanout: int) -> List[MatchedRoutes]:
        """Stage 3: escalation + interval expansion + overlay correction,
        all against the _InFlight SNAPSHOT (see _InFlight docstring)."""
        from ..ops.match import Probes, expand_intervals, walk_routes
        queries, ct, tok, roots = fl.queries, fl.ct, fl.tok, fl.roots
        # host-triggered escalation: rows whose active set (or interval
        # budget) overflowed re-walk in one compacted sub-batch at a
        # higher state budget AND a wider interval budget (a separate
        # dispatch, so its lane width is free to differ — the host merges
        # by slot arrays) — only rows that overflow even that fall
        # through to the host oracle
        esc_k = min(4 * self.k_states, 128)
        # never narrower than the base budget (a narrower re-walk is
        # guaranteed-futile for interval overflows)
        esc_a = max(min(4 * self.max_intervals, 256), self.max_intervals)
        esc_slots = {}
        ovf_rows = np.nonzero(overflow[:len(queries)]
                              & (tok.lengths[:len(queries)] >= 0))[0]
        if len(ovf_rows) and (esc_k > self.k_states
                              or esc_a > self.max_intervals):
            eb = _pow2_batch(len(ovf_rows))
            # ISSUE 11: sub_batch is polymorphic — host-tokenized
            # batches slice their rows; device-tokenized mirrors (whose
            # hash lanes never came back to host) re-tokenize just the
            # overflow rows
            sub = Probes.from_tokenized(tok.sub_batch(ovf_rows, eb),
                                        device=self.device)
            res2 = walk_routes(fl.dev, sub,
                               probe_len=ct.probe_len, k_states=esc_k,
                               max_intervals=esc_a, esc_k=0)
            o2 = np.asarray(res2.overflow)
            slots2, offs2 = expand_intervals(res2.start, res2.count)
            for j, qi in enumerate(ovf_rows):
                if not o2[j]:
                    esc_slots[int(qi)] = slots2[offs2[j]:offs2[j + 1]]
                    overflow[qi] = False
        # ISSUE 19: device-expanded batches hand the pairs pre-computed;
        # only buffer-truncated rows re-expand on host from the (lazily
        # fetched) interval grids — exact, just not pre-bucketed
        pairs = starts_a if isinstance(starts_a, _HostPairs) else None
        trunc_slots = trunc_offs = None
        trunc_map: dict = {}
        if pairs is not None:
            slots, offs = pairs.slots, pairs.row_offsets
            need = np.nonzero(pairs.trunc[:len(queries)]
                              & ~overflow[:len(queries)])[0]
            if len(need):
                g_s, g_c = self._fetch_escalation_grids(pairs.res)
                trunc_slots, trunc_offs = expand_intervals(
                    g_s[need], g_c[need])
                trunc_map = {int(qi): j for j, qi in enumerate(need)}
            self.last_expanded = (pairs, fl.peer_tab)
        else:
            slots, offs = expand_intervals(starts_a, counts_a)
        out: List[MatchedRoutes] = []
        for qi, (tenant_id, levels) in enumerate(queries):
            tomb = fl.tomb.get(tenant_id)
            delta = fl.delta.get(tenant_id)
            if roots[qi] < 0:
                # tenant absent from the base snapshot: all its routes (if
                # any) are newer than the base — serve from authoritative
                out.append(self.match_from_tries(
                    [(tenant_id, levels)],
                    max_persistent_fanout=max_persistent_fanout,
                    max_group_fanout=max_group_fanout)[0])
                continue
            if overflow[qi] or tok.lengths[qi] < 0:
                # even the fused device escalation overflowed (or the topic
                # is too deep for the walk shape): host oracle re-match
                out.append(self.match_from_tries(
                    [(tenant_id, levels)],
                    max_persistent_fanout=max_persistent_fanout,
                    max_group_fanout=max_group_fanout)[0])
                continue
            if qi in esc_slots:
                row = esc_slots[qi]
            elif qi in trunc_map:
                j = trunc_map[qi]
                row = trunc_slots[trunc_offs[j]:trunc_offs[j + 1]]
            else:
                row = slots[offs[qi]:offs[qi + 1]]
            if not tomb and delta is None:
                # fast path: no overlay for this tenant
                out.append(self._routes_from_slots(
                    ct, row, max_persistent_fanout, max_group_fanout))
                continue
            out.append(self._expand_with_overlay(
                ct, row, tomb or (), delta, _parse_levels(levels),
                max_persistent_fanout, max_group_fanout))
        return out

    def match(self, tenant_id: str, topic: str, **kwargs) -> MatchedRoutes:
        # ISSUE 11: the raw topic string flows through — the byte plane
        # tokenizes it; levels materialize only on fallback paths
        return self.match_batch([(tenant_id, topic)], **kwargs)[0]

    def match_from_tries(self, queries: Sequence[Tuple[str, Sequence[str]]],
                         *, max_persistent_fanout: int = UNCAPPED_FANOUT,
                         max_group_fanout: int = UNCAPPED_FANOUT
                         ) -> List[MatchedRoutes]:
        """Match straight from the authoritative host tries — the ONE
        exact-oracle fallback surface, shared by the walk's overflow path
        and the dist worker's fault/deadline degradation path (keeping
        their semantics identical by construction)."""
        out: List[MatchedRoutes] = []
        for tenant_id, levels in queries:
            trie = self.tries.get(tenant_id)
            out.append(trie.match(
                _parse_levels(levels),
                max_persistent_fanout=max_persistent_fanout,
                max_group_fanout=max_group_fanout)
                if trie is not None else MatchedRoutes())
        return out

    @staticmethod
    def _routes_from_slots(ct: CompiledTrie, row: np.ndarray,
                           max_persistent_fanout: int,
                           max_group_fanout: int) -> MatchedRoutes:
        """Slot ids → MatchedRoutes, caps applied vectorized.

        Same cap semantics as _expand (MatchedRoutes.java:38 rules) but all
        per-slot work is numpy: kind masks + cumsum ranks instead of a
        Python loop over slots. Group filters are unique per topic (one
        GroupMatching slot per (node, filter)), so a rank cutoff equals the
        reference's distinct-filter cap.
        """
        out = MatchedRoutes()
        if row.size == 0:
            return out
        kinds = ct.slot_kind[row]
        # ISSUE 9: tombstoned slots ride the interval until compaction
        # reclaims them — the walk emits them, this is where they die
        dead = kinds == CompiledTrie.SLOT_DEAD
        if dead.any():
            row, kinds = row[~dead], kinds[~dead]
            if row.size == 0:
                return out
        pers_mask = kinds == CompiledTrie.SLOT_PERSISTENT
        if (max_persistent_fanout != UNCAPPED_FANOUT
                and int(pers_mask.sum()) > max_persistent_fanout):
            out.max_persistent_fanout_exceeded = True
            drop = pers_mask & (np.cumsum(pers_mask)
                                > max_persistent_fanout)
            row, kinds, pers_mask = (row[~drop], kinds[~drop],
                                     pers_mask[~drop])
        out.persistent_fanout = int(pers_mask.sum())
        grp_mask = kinds == CompiledTrie.SLOT_GROUP
        arr = ct.matchings_arr
        if grp_mask.any():
            grp_slots = row[grp_mask]
            if (max_group_fanout != UNCAPPED_FANOUT
                    and grp_slots.size > max_group_fanout):
                out.max_group_fanout_exceeded = True
                grp_slots = grp_slots[:max_group_fanout]
            for m in arr[grp_slots]:
                out.groups[m.mqtt_topic_filter] = list(m.members)
            out.normal = arr[row[~grp_mask]].tolist()
        else:
            out.normal = arr[row].tolist()
        return out

    def _expand_with_overlay(self, ct: CompiledTrie, slots: np.ndarray,
                             tomb, delta: Optional[SubscriptionTrie],
                             levels: List[str],
                             max_persistent_fanout: int,
                             max_group_fanout: int) -> MatchedRoutes:
        """Base expansion ⊖ tombstones ⊕ delta matches, then caps.

        ``slots`` are matched slot ids from the interval walk (single-chip
        and mesh paths both expand intervals before calling)."""
        normal: List[Route] = []
        groups: Dict[str, List[Route]] = {}
        kind_arr = ct.slot_kind
        for slot in (int(s) for s in slots):
            if kind_arr[slot] == CompiledTrie.SLOT_DEAD:
                continue    # ISSUE 9: patch-tombstoned base slot
            m: Matching = ct.matchings[slot]
            if isinstance(m, GroupMatching):
                members = [r for r in m.members
                           if (m.mqtt_topic_filter, r.receiver_url)
                           not in tomb]
                if members:
                    groups[m.mqtt_topic_filter] = members
            else:
                if (m.matcher.mqtt_topic_filter, m.receiver_url) not in tomb:
                    normal.append(m)
        if delta is not None:
            dm = delta.match(levels)
            normal.extend(dm.normal)
            for f, members in dm.groups.items():
                groups.setdefault(f, []).extend(members)
        # caps over the merged set (MatchedRoutes.java:38 rules)
        out = MatchedRoutes()
        for r in normal:
            if r.broker_id == PERSISTENT_SUB_BROKER_ID:
                if out.persistent_fanout >= max_persistent_fanout:
                    out.max_persistent_fanout_exceeded = True
                    continue
                out.persistent_fanout += 1
            out.normal.append(r)
        for f, members in groups.items():
            if len(out.groups) >= max_group_fanout:
                out.max_group_fanout_exceeded = True
                continue
            out.groups[f] = members
        return out
