"""ctypes binding for the native topic tokenizer (native/tokenizer.cpp).

Hashes PUBLISH-topic levels into probe arrays ~20-40x faster than the Python
loop — the host-side ceiling flagged in round-1 perf notes. Bit-exact with
``automaton.level_hash`` (same BLAKE2b-8 + salt), enforced by parity tests.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native", "tokenizer.cpp")
_SO = os.path.join(os.path.dirname(_SRC), "libtokenizer.so")

# below this row count, thread spawn overhead beats the parallel win
_MT_THRESHOLD = 2048


def load_lib():
    from ..utils.nativelib import compile_and_load
    lib = compile_and_load(_SRC, _SO, extra_flags=("-pthread",))
    if not getattr(lib, "_tok_typed", False):
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        base_args = [
            u8p, i32p, ctypes.c_int, i32p, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_int, i32p, i32p, i32p, i32p, i32p, u8p, ctypes.c_int]
        lib.tok_topics.argtypes = base_args
        lib.tok_topics_mt.argtypes = base_args + [ctypes.c_int]
        lib._tok_typed = True
    return lib


def _pack(topics: Sequence) -> tuple:
    """Join level lists (or accept raw strings) into (bytes, offsets).

    ISSUE 11: a :class:`~bifromq_tpu.models.bytetok.TopicBytes` batch
    passes through untouched — the serving path packs ONCE per batch and
    this binding stops re-encoding what is already raw UTF-8."""
    from .bytetok import TopicBytes
    if isinstance(topics, TopicBytes):
        return topics.data, topics.offsets
    enc: List[bytes] = []
    for t in topics:
        if isinstance(t, bytes):
            enc.append(t)
        elif isinstance(t, str):
            enc.append(t.encode("utf-8"))
        else:
            enc.append("/".join(t).encode("utf-8"))
    offsets = np.zeros(len(enc) + 1, dtype=np.int32)
    np.cumsum([len(b) for b in enc], out=offsets[1:])
    return b"".join(enc), offsets


def tokenize_topics_native(topics: Sequence, roots: Sequence[int], *,
                           max_levels: int, salt: int,
                           batch: Optional[int] = None,
                           filter_mode: bool = False):
    """Native-equivalent of automaton.tokenize / tokenize_filters.

    ``topics`` may be str / bytes / level-list rows or one pre-packed
    ``TopicBytes`` batch (the byte-plane serving path). Returns
    (tok_h1, tok_h2, tok_kind, lengths, roots, sys_mask) numpy arrays;
    tok_kind is None unless ``filter_mode``.
    """
    lib = load_lib()
    n = len(topics)
    b = batch or n
    assert b >= n
    width = max_levels + 1
    data, offsets = _pack(topics)
    if isinstance(data, np.ndarray):
        data_arr = (np.ascontiguousarray(data, dtype=np.uint8)
                    if data.size else np.zeros(1, dtype=np.uint8))
        offsets = np.ascontiguousarray(offsets, dtype=np.int32)
    else:
        data_arr = np.frombuffer(data, dtype=np.uint8) if data else \
            np.zeros(1, dtype=np.uint8)
    roots_arr = np.asarray(list(roots), dtype=np.int32)
    tok_h1 = np.zeros((b, width), dtype=np.int32)
    tok_h2 = np.zeros((b, width), dtype=np.int32)
    tok_kind = np.zeros((b, width), dtype=np.int32) if filter_mode else None
    lengths = np.full(b, -1, dtype=np.int32)
    root_out = np.full(b, -1, dtype=np.int32)
    sys_mask = np.zeros(b, dtype=np.uint8)

    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)

    def p32(a):
        return a.ctypes.data_as(i32p)

    args = (
        data_arr.ctypes.data_as(u8p), p32(offsets), n, p32(roots_arr),
        max_levels, ctypes.c_uint64(salt & 0xFFFFFFFFFFFFFFFF),
        int(filter_mode), p32(tok_h1), p32(tok_h2),
        p32(tok_kind) if tok_kind is not None else i32p(),
        p32(lengths), p32(root_out), sys_mask.ctypes.data_as(u8p), width)
    if n >= _MT_THRESHOLD:
        # rows are independent; ctypes releases the GIL for the whole call
        lib.tok_topics_mt(*args, min(8, os.cpu_count() or 1))
    else:
        lib.tok_topics(*args)
    return tok_h1, tok_h2, tok_kind, lengths, root_out, sys_mask.astype(bool)
