"""Fused Pallas TPU trie-walk kernel (ISSUE 6 tentpole part 3).

The lax serving walk (``ops.match.walk_routes``) lowers to a *sequence*
of XLA ops — per-level hash-mix, bucket-row gather, successor compaction,
interval emission, final cumsum/scatter pack — which XLA is free to
schedule as many kernel launches with intermediate HBM round-trips. This
module fuses the whole per-batch pipeline — token hash-mix → level walk →
slot-interval gather → compaction — into ONE ``pl.pallas_call`` (the
SNIPPETS [2] Pallas-TPU idiom, and the single-launch trie-walk shape of
TrieJax / "Vectorizing the Trie", PAPERS.md), so the walk state lives in
VMEM for the whole launch instead of bouncing through HBM between stages.

Semantics: the kernel body REUSES ``ops.match._route_walk`` — the exact
step/compaction math of the lax walk — operating on refs instead of HBM
arrays. Row-identical output to ``walk_routes(..., esc_k=0)`` is
therefore by construction, and the parity suite (tests/test_kernels.py)
enforces it against both the lax walk and the host oracle.

Deployment gates (all are consulted by ``fused_enabled``):

- ``BIFROMQ_FUSED_KERNEL`` env: ``0``/``off`` kills the fused path
  everywhere (the ISSUE 6 kill-switch); ``1``/``on`` forces it on every
  backend (interpreter mode off-TPU); unset/``auto`` enables it only on
  a real TPU backend — the interpreter is a correctness surface, not a
  serving surface, and the lax walk is faster on CPU.
- VMEM capacity: the single-launch kernel keeps the automaton tables
  resident in VMEM, so it only compiles when the table bytes fit
  ``BIFROMQ_FUSED_VMEM_MB`` (default 12 MB of the ~16 MB/core budget);
  bigger automatons fall back to the lax walk (auto mode) — the
  multi-chip sharding item (ROADMAP) is what shrinks per-core tables.

Incremental patching (ISSUE 9): the fused walk reads the SAME patched
arenas as the lax walk — ``edge_tab``/``route_tab`` are passed per call,
so a narrow patch flush (models/matcher._flush_patches) is visible on
the very next launch with no rebuild. The ``_build_fused`` cache keys on
table SHAPES, and the patchable arenas carry pow2 growth headroom
precisely so steady churn never reshapes them: patches reuse the cached
kernel, and only an arena growth / edge-table regrow (pow2-amortized)
re-traces. The VMEM gate weighs the PADDED table bytes — headroom rows
are resident whether or not they're live, so that is the honest number.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..ops.match import (DeviceTrie, Probes, RouteIntervals, _route_walk,
                         device_expand_enabled)
from ..utils.env import env_int, env_str

_VMEM_BUDGET_MB_DEFAULT = 12


def _env_mode() -> str:
    v = env_str("BIFROMQ_FUSED_KERNEL", "auto").lower()
    if v in ("0", "off", "false"):
        return "off"
    if v in ("1", "on", "true"):
        return "on"
    return "auto"


def fused_vmem_budget_bytes() -> int:
    # fused_enabled runs on every serving dispatch: a malformed knob
    # falls back to the default (env_int), never crashes the match path
    return env_int("BIFROMQ_FUSED_VMEM_MB",
                   _VMEM_BUDGET_MB_DEFAULT) * (1 << 20)


def _table_bytes(trie: DeviceTrie) -> int:
    total = 0
    for a in (trie.edge_tab, trie.route_tab):
        if a is not None:
            total += a.size * a.dtype.itemsize
    return total


def fused_table_bytes(trie: DeviceTrie) -> int:
    """The bytes the VMEM gate weighs (edge + route tables — the two the
    kernel keeps resident). Public so the capacity plane (obs/capacity)
    reports the same number the gate compares."""
    return _table_bytes(trie)


def fused_fits_vmem(table_bytes: int) -> bool:
    """THE VMEM-capacity comparison — one definition shared by the
    serving gate below and the capacity planner's predicted verdict
    (ISSUE 8): a planner that re-derived the comparison could drift from
    what the dispatch path actually does."""
    return table_bytes <= fused_vmem_budget_bytes()


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # noqa: BLE001 — backend init failure = no device
        return False


def fused_enabled(trie: Optional[DeviceTrie] = None) -> bool:
    """Should the serving walk route through the fused kernel?

    Read per-dispatch (cheap: one env read + a size check) so tests and
    operators can flip ``BIFROMQ_FUSED_KERNEL`` on a live process.
    """
    mode = _env_mode()
    if mode == "off":
        return False
    if mode == "on":
        return True
    # auto: compiled TPU only, and only when the tables fit VMEM
    if not _on_tpu():
        return False
    if trie is not None and not fused_fits_vmem(_table_bytes(trie)):
        return False
    return True


@functools.lru_cache(maxsize=64)
def _build_fused(b: int, width: int, nb: int, probe_len: int, n_nodes: int,
                 rt_cols: int, k_states: int, compaction: str,
                 max_intervals: int, interpret: bool):
    """One compiled fused walk per (shape, config) class.

    The pallas_call is rebuilt per shape class exactly like jit re-traces
    per shape; the lru_cache plays the role of jit's trace cache.
    """
    from jax.experimental import pallas as pl

    def kernel(edge_ref, route_ref, t1_ref, t2_ref, len_ref, roots_ref,
               sys_ref, ivl_s_ref, ivl_c_ref, nr_ref, ovf_ref):
        # the tables load once into kernel memory and every walk stage —
        # hash-mix, bucket probe, successor compaction, interval emission,
        # final pack — runs inside this single launch. node_tab is the
        # route_tab view: _route_walk only reads RT_* columns and the
        # _advance plus-child contract pins RT_PLUS at column 0.
        tab = route_ref[...]
        trie = DeviceTrie(node_tab=tab, edge_tab=edge_ref[...],
                          child_list=None, route_tab=tab)
        probes = Probes(t1_ref[...], t2_ref[...], len_ref[...],
                        roots_ref[...], sys_ref[...])
        s, c, nr, ovf = _route_walk(trie, probes, probe_len, k_states,
                                    compaction, max_intervals)
        ivl_s_ref[...] = s
        ivl_c_ref[...] = c
        nr_ref[...] = nr
        ovf_ref[...] = ovf

    call = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, max_intervals), jnp.int32),
            jax.ShapeDtypeStruct((b, max_intervals), jnp.int32),
            jax.ShapeDtypeStruct((b,), jnp.int32),
            jax.ShapeDtypeStruct((b,), jnp.bool_),
        ),
        interpret=interpret,
    )
    return jax.jit(lambda e, r, t1, t2, ln, ro, sm: call(
        e, r, t1, t2, ln, ro, sm))


def fused_walk_routes(trie: DeviceTrie, probes: Probes, *, probe_len: int,
                      k_states: int = 32, compaction: str = "sort",
                      max_intervals: int = 32,
                      interpret: Optional[bool] = None) -> RouteIntervals:
    """The fused single-launch serving walk.

    Drop-in for ``walk_routes(..., esc_k=0)`` (no on-device escalation —
    the matcher's host-triggered escalation re-walks overflow rows through
    this same entry at a higher budget). ``interpret=None`` auto-selects
    interpreter mode off-TPU (the CPU fallback the ISSUE requires).
    """
    if trie.route_tab is None:
        raise ValueError("fused walk requires DeviceTrie.route_tab")
    if interpret is None:
        interpret = not _on_tpu()
    b, width = probes.tok_h1.shape
    fn = _build_fused(b, width, int(trie.edge_tab.shape[0]), probe_len,
                      int(trie.route_tab.shape[0]),
                      int(trie.route_tab.shape[1]), k_states, compaction,
                      max_intervals, bool(interpret))
    s, c, nr, ovf = fn(trie.edge_tab, trie.route_tab, probes.tok_h1,
                       probes.tok_h2, probes.lengths, probes.roots,
                       probes.sys_mask)
    return RouteIntervals(start=s, count=c, n_routes=nr, overflow=ovf)


# ---------------- device fan-out expansion stage (ISSUE 19) -----------------
#
# The second kernel stage after the walk: ragged-arange expansion of the
# [B, A] interval grids into dense (slot, row) pairs. Unlike the lax
# expansion in ops.match._expand_pairs (scatter-mark + running max — the
# shape XLA fuses well on CPU), the kernel formulation is a per-element
# binary search over the lane end-offsets: the prefix sums load into VMEM
# once and every output position resolves its owning lane in log2(n)
# steps inside one launch — no scatter, no scan, no HBM bounce between
# the search and the gather.


def expand_kernel_enabled() -> bool:
    """Route the expansion stage through the Pallas kernel? Compiled TPU
    only — off-TPU the interpreter is a correctness surface (the parity
    tests run it explicitly) and the lax expansion is the serving path."""
    return device_expand_enabled() and _on_tpu()


@functools.lru_cache(maxsize=64)
def _build_expand(n: int, cap: int, a: int, interpret: bool):
    """One compiled expansion per (lane-count, capacity, lane-width)
    shape class — same cache-plays-jit role as _build_fused."""
    from jax.experimental import pallas as pl

    nbits = max(1, n.bit_length())    # n is a static python int

    def kernel(ends_ref, lo_ref, s_ref, slots_ref, rows_ref):
        ends = ends_ref[...]
        lane_lo = lo_ref[...]
        flat_s = s_ref[...]
        # 2D broadcasted_iota: 1D iota does not lower on TPU
        j = jax.lax.broadcasted_iota(jnp.int32, (cap, 1), 0)[:, 0]

        # searchsorted-right: smallest lane with ends[lane] > j. Empty
        # lanes alias their predecessor's end offset and are skipped by
        # the strict comparison automatically.
        def body(_, carry):
            lo, hi = carry
            mid = (lo + hi) // 2
            right = ends[mid.clip(0, n - 1)] <= j
            return (jnp.where(right, mid + 1, lo),
                    jnp.where(right, hi, mid))

        lo, _hi = jax.lax.fori_loop(
            0, nbits, body, (jnp.zeros((cap,), jnp.int32),
                             jnp.full((cap,), n, jnp.int32)))
        lane = lo.clip(0, n - 1)
        valid = j < jnp.minimum(ends[n - 1], cap)
        slots_ref[...] = jnp.where(
            valid, flat_s[lane] + (j - lane_lo[lane]), -1)
        rows_ref[...] = jnp.where(valid, lane // a, -1)

    call = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((cap,), jnp.int32),
            jax.ShapeDtypeStruct((cap,), jnp.int32),
        ),
        interpret=interpret,
    )
    return jax.jit(lambda ends, lo, s: call(ends, lo, s))


def pallas_expand(ivl_s, ivl_c, *, cap: int,
                  interpret: Optional[bool] = None):
    """Kernel twin of ``ops.match._expand_pairs`` — identical output
    contract: (slots [cap], rows [cap], row_offsets [B+1], n_pairs [],
    trunc [B]) in the host expander's row-major order. The O(B·A) prefix
    sums stay in lax (they are trivial); only the O(cap) expansion runs
    in the kernel. Traceable: safe to call under an outer jit."""
    b, a = ivl_s.shape
    n = b * a
    flat_c = jnp.maximum(ivl_c.reshape(n), 0)
    flat_s = ivl_s.reshape(n)
    ends = jnp.cumsum(flat_c, dtype=jnp.int32)
    lane_lo = ends - flat_c
    row_offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), ends.reshape(b, a)[:, -1]])
    trunc = row_offsets[1:] > cap
    if interpret is None:
        interpret = not _on_tpu()
    slots, rows = _build_expand(n, cap, a, bool(interpret))(
        ends, lane_lo, flat_s)
    return slots, rows, row_offsets, jnp.minimum(ends[n - 1], cap), trunc


# ---------------- inter-chip right_permute (ISSUE 19 mesh leg) ---------------
#
# The mesh expand step merges per-peer delivery counts across shards with a
# ring of single-neighbor right-rotate hops instead of the all-reduce psum
# the walk step used to pay. Each hop is one interconnect transfer; on a
# real TPU it lowers to a Pallas RDMA kernel (make_async_remote_copy, the
# SNIPPETS [2] right_permute shape) so the transfer is a direct chip-to-chip
# DMA with send/recv semaphores — off-TPU the caller uses jax.lax.ppermute,
# which is both the CPU-emulation path and the parity oracle for this
# kernel.


def rdma_permute_enabled() -> bool:
    """Route mesh ring hops through the RDMA kernel? Compiled TPU only —
    there is no interconnect to DMA over anywhere else, and ppermute is
    the exact same rotation."""
    return device_expand_enabled() and _on_tpu()


def pallas_right_permute(x, axis_name: str, axis_names):
    """One right-rotate hop over ``axis_name``: ship this device's block
    to its ring successor and receive the predecessor's, as a single
    remote DMA. Must be traced inside a shard_map over ``axis_names``
    (the full mesh axis tuple, so the neighbor coordinate is exact on a
    2D replica×shard mesh)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(in_ref, out_ref, send_sem, recv_sem):
        size = jax.lax.psum(1, axis_name)
        rot = axis_names.index(axis_name)
        # full mesh coordinate of the right neighbor: rotate only the
        # ring axis, keep the others (LOGICAL ids are mesh coordinates)
        device_id = tuple(
            jnp.remainder(jax.lax.axis_index(a) + 1, size)
            if i == rot else jax.lax.axis_index(a)
            for i, a in enumerate(axis_names))
        rdma = pltpu.make_async_remote_copy(
            src_ref=in_ref, dst_ref=out_ref,
            send_sem=send_sem, recv_sem=recv_sem,
            device_id=device_id,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        rdma.start()
        rdma.wait()

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
        compiler_params=pltpu.TPUCompilerParams(collective_id=0),
    )(x)
