"""Fused Pallas TPU trie-walk kernel (ISSUE 6 tentpole part 3).

The lax serving walk (``ops.match.walk_routes``) lowers to a *sequence*
of XLA ops — per-level hash-mix, bucket-row gather, successor compaction,
interval emission, final cumsum/scatter pack — which XLA is free to
schedule as many kernel launches with intermediate HBM round-trips. This
module fuses the whole per-batch pipeline — token hash-mix → level walk →
slot-interval gather → compaction — into ONE ``pl.pallas_call`` (the
SNIPPETS [2] Pallas-TPU idiom, and the single-launch trie-walk shape of
TrieJax / "Vectorizing the Trie", PAPERS.md), so the walk state lives in
VMEM for the whole launch instead of bouncing through HBM between stages.

Semantics: the kernel body REUSES ``ops.match._route_walk`` — the exact
step/compaction math of the lax walk — operating on refs instead of HBM
arrays. Row-identical output to ``walk_routes(..., esc_k=0)`` is
therefore by construction, and the parity suite (tests/test_kernels.py)
enforces it against both the lax walk and the host oracle.

Deployment gates (all are consulted by ``fused_enabled``):

- ``BIFROMQ_FUSED_KERNEL`` env: ``0``/``off`` kills the fused path
  everywhere (the ISSUE 6 kill-switch); ``1``/``on`` forces it on every
  backend (interpreter mode off-TPU); unset/``auto`` enables it only on
  a real TPU backend — the interpreter is a correctness surface, not a
  serving surface, and the lax walk is faster on CPU.
- VMEM capacity: the single-launch kernel keeps the automaton tables
  resident in VMEM, so it only compiles when the table bytes fit
  ``BIFROMQ_FUSED_VMEM_MB`` (default 12 MB of the ~16 MB/core budget);
  bigger automatons fall back to the lax walk (auto mode) — the
  multi-chip sharding item (ROADMAP) is what shrinks per-core tables.

Incremental patching (ISSUE 9): the fused walk reads the SAME patched
arenas as the lax walk — ``edge_tab``/``route_tab`` are passed per call,
so a narrow patch flush (models/matcher._flush_patches) is visible on
the very next launch with no rebuild. The ``_build_fused`` cache keys on
table SHAPES, and the patchable arenas carry pow2 growth headroom
precisely so steady churn never reshapes them: patches reuse the cached
kernel, and only an arena growth / edge-table regrow (pow2-amortized)
re-traces. The VMEM gate weighs the PADDED table bytes — headroom rows
are resident whether or not they're live, so that is the honest number.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..ops.match import DeviceTrie, Probes, RouteIntervals, _route_walk
from ..utils.env import env_int, env_str

_VMEM_BUDGET_MB_DEFAULT = 12


def _env_mode() -> str:
    v = env_str("BIFROMQ_FUSED_KERNEL", "auto").lower()
    if v in ("0", "off", "false"):
        return "off"
    if v in ("1", "on", "true"):
        return "on"
    return "auto"


def fused_vmem_budget_bytes() -> int:
    # fused_enabled runs on every serving dispatch: a malformed knob
    # falls back to the default (env_int), never crashes the match path
    return env_int("BIFROMQ_FUSED_VMEM_MB",
                   _VMEM_BUDGET_MB_DEFAULT) * (1 << 20)


def _table_bytes(trie: DeviceTrie) -> int:
    total = 0
    for a in (trie.edge_tab, trie.route_tab):
        if a is not None:
            total += a.size * a.dtype.itemsize
    return total


def fused_table_bytes(trie: DeviceTrie) -> int:
    """The bytes the VMEM gate weighs (edge + route tables — the two the
    kernel keeps resident). Public so the capacity plane (obs/capacity)
    reports the same number the gate compares."""
    return _table_bytes(trie)


def fused_fits_vmem(table_bytes: int) -> bool:
    """THE VMEM-capacity comparison — one definition shared by the
    serving gate below and the capacity planner's predicted verdict
    (ISSUE 8): a planner that re-derived the comparison could drift from
    what the dispatch path actually does."""
    return table_bytes <= fused_vmem_budget_bytes()


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # noqa: BLE001 — backend init failure = no device
        return False


def fused_enabled(trie: Optional[DeviceTrie] = None) -> bool:
    """Should the serving walk route through the fused kernel?

    Read per-dispatch (cheap: one env read + a size check) so tests and
    operators can flip ``BIFROMQ_FUSED_KERNEL`` on a live process.
    """
    mode = _env_mode()
    if mode == "off":
        return False
    if mode == "on":
        return True
    # auto: compiled TPU only, and only when the tables fit VMEM
    if not _on_tpu():
        return False
    if trie is not None and not fused_fits_vmem(_table_bytes(trie)):
        return False
    return True


@functools.lru_cache(maxsize=64)
def _build_fused(b: int, width: int, nb: int, probe_len: int, n_nodes: int,
                 rt_cols: int, k_states: int, compaction: str,
                 max_intervals: int, interpret: bool):
    """One compiled fused walk per (shape, config) class.

    The pallas_call is rebuilt per shape class exactly like jit re-traces
    per shape; the lru_cache plays the role of jit's trace cache.
    """
    from jax.experimental import pallas as pl

    def kernel(edge_ref, route_ref, t1_ref, t2_ref, len_ref, roots_ref,
               sys_ref, ivl_s_ref, ivl_c_ref, nr_ref, ovf_ref):
        # the tables load once into kernel memory and every walk stage —
        # hash-mix, bucket probe, successor compaction, interval emission,
        # final pack — runs inside this single launch. node_tab is the
        # route_tab view: _route_walk only reads RT_* columns and the
        # _advance plus-child contract pins RT_PLUS at column 0.
        tab = route_ref[...]
        trie = DeviceTrie(node_tab=tab, edge_tab=edge_ref[...],
                          child_list=None, route_tab=tab)
        probes = Probes(t1_ref[...], t2_ref[...], len_ref[...],
                        roots_ref[...], sys_ref[...])
        s, c, nr, ovf = _route_walk(trie, probes, probe_len, k_states,
                                    compaction, max_intervals)
        ivl_s_ref[...] = s
        ivl_c_ref[...] = c
        nr_ref[...] = nr
        ovf_ref[...] = ovf

    call = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, max_intervals), jnp.int32),
            jax.ShapeDtypeStruct((b, max_intervals), jnp.int32),
            jax.ShapeDtypeStruct((b,), jnp.int32),
            jax.ShapeDtypeStruct((b,), jnp.bool_),
        ),
        interpret=interpret,
    )
    return jax.jit(lambda e, r, t1, t2, ln, ro, sm: call(
        e, r, t1, t2, ln, ro, sm))


def fused_walk_routes(trie: DeviceTrie, probes: Probes, *, probe_len: int,
                      k_states: int = 32, compaction: str = "sort",
                      max_intervals: int = 32,
                      interpret: Optional[bool] = None) -> RouteIntervals:
    """The fused single-launch serving walk.

    Drop-in for ``walk_routes(..., esc_k=0)`` (no on-device escalation —
    the matcher's host-triggered escalation re-walks overflow rows through
    this same entry at a higher budget). ``interpret=None`` auto-selects
    interpreter mode off-TPU (the CPU fallback the ISSUE requires).
    """
    if trie.route_tab is None:
        raise ValueError("fused walk requires DeviceTrie.route_tab")
    if interpret is None:
        interpret = not _on_tpu()
    b, width = probes.tok_h1.shape
    fn = _build_fused(b, width, int(trie.edge_tab.shape[0]), probe_len,
                      int(trie.route_tab.shape[0]),
                      int(trie.route_tab.shape[1]), k_states, compaction,
                      max_intervals, bool(interpret))
    s, c, nr, ovf = fn(trie.edge_tab, trie.route_tab, probes.tok_h1,
                       probes.tok_h2, probes.lengths, probes.roots,
                       probes.sys_mask)
    return RouteIntervals(start=s, count=c, n_routes=nr, overflow=ovf)
