"""Match-result cache plane (ISSUE 4 tentpole).

The reference broker fronts its trie walk with a ``TenantRouteCache`` /
``SubscriptionCache`` (bifromq-dist-worker .../cache/TenantRouteCache.java:65)
on the bet that publish topics repeat: a repeated (tenant, topic) never
re-matches. This module is that plane for the TPU port — a per-tenant LRU
of expanded ``MatchedRoutes`` keyed by topic, consulted *before* any
tokenization, padding, or device dispatch.

Invalidation is **filter-aware**, mirroring the reference's
refresh-on-mutation contract (TenantRouteCache.java:100-160):

- an **exact** filter (no ``+``/``#`` level) can only change the match
  result of the one topic equal to its levels → evict just that topic key;
- a **wildcard** filter intersects an unbounded topic set → bump the
  tenant's epoch (O(1) wholesale invalidation; stale entries die lazily);
- a base rebuild (overlay compaction / salt-change recompile / reset)
  bumps a global generation → every tenant's entries go stale at once.

Writes racing reads: ``token()`` snapshots the tenant's (generation,
epoch, mutation-seq) *before* the match is issued; ``put`` refuses the
store when any invalidation landed in between — a mutation during an
awaited match can therefore never be erased by stamping a stale result
with the post-bump state (the dist service's pub path awaits its match
across the event loop; the matcher's own path is synchronous but shares
the discipline).

Two deployments of the same class:

- ``TpuMatcher`` (scope ``"matcher"``): authoritative per-range cache, no
  TTL — every mutation flows through the owning matcher, so epoch/evict
  invalidation is complete;
- ``DistService`` (scope ``"pub"``): frontend pub-side cache with a TTL
  that bounds staleness from mutations applied on OTHER nodes when the
  worker is remote (the reference's refresh window); with a local worker
  the coproc's apply-stream hook makes invalidation exact there too.

Counters feed the process-global ``utils.metrics.MATCH_CACHE`` section
(``/metrics`` ``"match_cache"``) per scope; per-tenant hit rates ride the
OBS windowed SLO layer into ``GET /tenants``.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Sequence, Tuple

from ..utils import topic as topic_util

# invalidation token: (generation, tenant epoch, tenant mutation seq)
Token = Tuple[int, int, int]

_WILDCARDS = (topic_util.SINGLE_WILDCARD, topic_util.MULTI_WILDCARD)


def filter_is_wildcard(filter_levels: Sequence[str]) -> bool:
    """True when the filter can match more than one concrete topic."""
    return any(level in _WILDCARDS for level in filter_levels)


class _TenantSlot:
    __slots__ = ("epoch", "seq", "entries")

    def __init__(self, seq0: int) -> None:
        self.epoch = 0
        # every seq value a slot ever holds is a UNIQUE draw from the
        # cache-wide monotone source (creation here, every invalidation
        # below): a slot dropped by the tenant-cardinality bound and later
        # recreated can therefore never alias a token snapshotted against
        # its previous life, no matter how the interleaving goes
        self.seq = seq0
        # topic key -> (generation, epoch, expires, caps, MatchedRoutes);
        # ONE caps variant per topic (caps are per-tenant settings and
        # effectively constant — a caps change is a miss + overwrite),
        # which keeps exact-filter eviction a single dict pop.
        self.entries: Dict[object, Tuple] = {}


class TenantMatchCache:
    """Per-tenant LRU of expanded match results with filter-aware
    invalidation (see module docstring). Topic keys are either parsed
    level tuples (matcher plane) or raw topic strings (pub plane); both
    forms are evicted by exact-filter invalidation."""

    def __init__(self, *, scope: str = "matcher",
                 max_topics_per_tenant: int = 8192,
                 max_tenants: int = 4096,
                 max_entries: int = 1 << 16,
                 ttl_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 metrics=None) -> None:
        self.scope = scope
        self.max_topics_per_tenant = max_topics_per_tenant
        self.max_tenants = max_tenants
        # hard TOTAL bound across all tenants: per-tenant LRU alone would
        # let max_tenants × max_topics_per_tenant MatchedRoutes accumulate
        # (TTL expiry is lazy); past the bound the oldest-inserted
        # tenant's oldest entries go first
        self.max_entries = max_entries
        self.ttl_s = ttl_s
        self._clock = clock
        self._gen = 0
        self._seq_src = 1
        self._total = 0
        self._slots: Dict[str, _TenantSlot] = {}
        if metrics is None:
            from ..utils.metrics import MATCH_CACHE
            metrics = MATCH_CACHE
        self._metrics = metrics
        # instance counters (bench A/B + per-range span tags); the global
        # section aggregates across instances
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.epoch_bumps = 0

    def __len__(self) -> int:
        return self._total

    # ---------------- lookup ------------------------------------------------

    def _next_seq(self) -> int:
        v = self._seq_src
        self._seq_src += 1
        return v

    def _drop_oldest_slot(self, keep: Optional[str] = None) -> None:
        victim = next(k for k in self._slots if k != keep)
        dropped = self._slots.pop(victim)
        self._total -= len(dropped.entries)
        self._count_evictions(len(dropped.entries))

    def _evict_entry(self, keep: Optional[str] = None) -> bool:
        """Evict ONE entry — the oldest-created other tenant's oldest —
        for the total bound (a whole-slot drop here would be a cliff:
        one insert annihilating another tenant's entire working set).
        Empty token()-materialized slots passed on the way are reaped."""
        empties = []
        victim = None
        for t, s in self._slots.items():
            if t == keep:
                continue
            if not s.entries:
                empties.append(t)
                continue
            victim = t
            break
        for t in empties:
            # safe to reap: nothing cached, and a recreated slot draws a
            # fresh seq so in-flight tokens against it stay refused
            del self._slots[t]
        if victim is None:
            return False
        s = self._slots[victim]
        s.entries.pop(next(iter(s.entries)))
        self._total -= 1
        if not s.entries:
            del self._slots[victim]
        self._count_evictions(1)
        return True

    def _slot(self, tenant: str) -> _TenantSlot:
        s = self._slots.get(tenant)
        if s is None:
            if len(self._slots) >= self.max_tenants:
                # bounded tenant cardinality: drop the oldest-inserted
                # tenant's slot (dict FIFO, the codebase-wide discipline)
                self._drop_oldest_slot()
            s = self._slots.setdefault(tenant,
                                       _TenantSlot(self._next_seq()))
        return s

    def token(self, tenant: str) -> Token:
        """Invalidation snapshot to take BEFORE issuing the match whose
        result will be ``put`` under it. Materializes the tenant's slot so
        a mutation landing mid-flight always has a seq to bump."""
        s = self._slot(tenant)
        return (self._gen, s.epoch, s.seq)

    def get(self, tenant: str, topic_key, caps: Tuple[int, int]):
        """Cached MatchedRoutes for (tenant, topic) under ``caps``, or
        None. Callers treat the returned object as READ-ONLY (the same
        result object fans out to every hit). Hit/miss totals are pushed
        to the global section by the batch-level call sites (one inc per
        batch), not here — a per-row global-lock round-trip would tax the
        very hot path this cache exists to shorten."""
        s = self._slots.get(tenant)
        ent = s.entries.get(topic_key) if s is not None else None
        if ent is not None:
            gen, epoch, expires, ecaps, m = ent
            if (gen == self._gen and epoch == s.epoch and ecaps == caps
                    and (expires is None or self._clock() < expires)):
                # true LRU: refresh recency (dict insertion order)
                del s.entries[topic_key]
                s.entries[topic_key] = ent
                self.hits += 1
                return m
            del s.entries[topic_key]  # stale under any clause: drop now
            self._total -= 1
        self.misses += 1
        return None

    def put(self, tenant: str, topic_key, caps: Tuple[int, int], result,
            token: Token) -> bool:
        """Store a match result under the pre-match ``token``; refused
        (returns False) when any invalidation landed since the snapshot."""
        s = self._slot(tenant)
        if token != (self._gen, s.epoch, s.seq):
            return False
        if topic_key not in s.entries:
            if len(s.entries) >= self.max_topics_per_tenant:
                # amortized sweep: drop the oldest quarter (insertion
                # order ≈ LRU because get() refreshes recency)
                drop = max(1, len(s.entries) // 4)
                for k in list(s.entries)[:drop]:
                    del s.entries[k]
                self._total -= drop
                self._count_evictions(drop)
            while (self._total >= self.max_entries
                   and self._evict_entry(keep=tenant)):
                pass
            if self._total >= self.max_entries and s.entries:
                # this tenant holds the whole budget: its oldest out
                s.entries.pop(next(iter(s.entries)))
                self._total -= 1
                self._count_evictions(1)
            self._total += 1
        expires = (self._clock() + self.ttl_s
                   if self.ttl_s is not None else None)
        s.entries[topic_key] = (self._gen, s.epoch, expires, caps, result)
        return True

    # ---------------- invalidation -----------------------------------------

    def invalidate(self, tenant: str,
                   filter_levels: Sequence[str]) -> None:
        """Filter-aware invalidation for one route mutation: exact filters
        evict just the matching topic keys; wildcard filters bump the
        tenant epoch wholesale."""
        if filter_is_wildcard(filter_levels):
            self.bump(tenant)
            return
        s = self._slots.get(tenant)
        if s is None:
            return
        # fresh draw (never +=1): defeats in-flight puts AND keeps every
        # seq value globally unique (see _TenantSlot)
        s.seq = self._next_seq()
        n = 0
        # all three key forms: parsed level tuple, raw topic string
        # (ISSUE 11 serving path), and raw wire bytes
        joined = topic_util.DELIMITER.join(filter_levels)
        for key in (tuple(filter_levels), joined,
                    joined.encode("utf-8")):
            if s.entries.pop(key, None) is not None:
                n += 1
        if n:
            self._total -= n
            self._count_evictions(n)

    def bump(self, tenant: str) -> None:
        """Wholesale per-tenant invalidation (wildcard mutation, or a
        mutation whose filter is unknown)."""
        s = self._slots.get(tenant)
        if s is None:
            return
        s.epoch += 1
        s.seq = self._next_seq()
        self.epoch_bumps += 1
        self._metrics.inc(self.scope, "epoch_bumps")

    def bump_all(self) -> None:
        """Global invalidation: base rebuild (overlay compaction / salt
        change) or reset-from-KV — every tenant's entries go stale."""
        self._gen += 1
        self.epoch_bumps += 1
        self._metrics.inc(self.scope, "epoch_bumps")

    def _count_evictions(self, n: int) -> None:
        self.evictions += n
        self._metrics.inc(self.scope, "evictions", n)

    # ---------------- introspection ----------------------------------------

    def hot_keys(self, k: int = 16):
        """Up to ``k`` most-recently-served (tenant, topic) pairs — the
        digest's hot-topic key set (ISSUE 12): ``get`` refreshes dict
        recency, so each slot's tail is its hottest working set. Keys
        normalize to topic strings (level tuples re-join, wire bytes
        decode) so the set is gossip/JSON-safe and a pre-warming replica
        can replay them as plain match queries."""
        from itertools import islice, zip_longest
        per_tenant = max(1, k // max(1, len(self._slots)))
        # O(per_tenant) tail walk per tenant — never a full key-list
        # copy per gossip tick (a full cache holds 64k entries); the
        # round-robin interleave below gives EVERY tenant its hottest
        # key before any tenant gets a second (more tenants than k must
        # not silently drop the earliest-created — possibly hottest —
        # slots on dict insertion order)
        tails = [[(tenant, key)
                  for key in islice(reversed(s.entries), per_tenant)]
                 for tenant, s in self._slots.items()]
        out = []
        for rank in zip_longest(*tails):
            for pair in rank:
                if pair is None:
                    continue
                tenant, key = pair
                if isinstance(key, bytes):
                    key = key.decode("utf-8", "replace")
                elif isinstance(key, tuple):
                    key = topic_util.DELIMITER.join(key)
                out.append([tenant, key])
                if len(out) >= k:
                    return out
        return out

    def counts(self) -> Tuple[int, int]:
        return self.hits, self.misses

    def snapshot(self) -> Dict[str, float]:
        lookups = self.hits + self.misses
        return {"hits": self.hits, "misses": self.misses,
                "evictions": self.evictions,
                "epoch_bumps": self.epoch_bumps,
                "hit_rate": round(self.hits / lookups, 4) if lookups
                else 0.0,
                "entries": len(self)}

    def clear(self) -> None:
        self._slots.clear()
        self._total = 0
