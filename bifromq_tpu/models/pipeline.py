"""Async device-dispatch ring (ISSUE 6 tentpole part 1).

BENCH_r01 measured the sync serving path at p50 ≈ 666ms per batch: every
publish paid `batcher queue → pow2 pad → device dispatch → BLOCKING
device_get` with nothing overlapped. This module is the overlap plane:

- a **dispatch ring** bounds the number of in-flight device batches
  (``BIFROMQ_PIPELINE_DEPTH``, default 2 = double-buffered; 3 = triple):
  batch N+1 tokenizes and enqueues on device while batch N is still
  walking, because the await happens on *readiness*, not inside dispatch;
- results come back via **fetch-on-ready**: the dispatch starts a
  ``copy_to_host_async`` immediately, the serving coroutine polls
  ``jax.Array.is_ready`` (yielding the event loop between polls — other
  batches dispatch in those gaps) and only then pays the final host copy;
- the ring's occupancy is the **queue-depth signal** for adaptive batch
  shaping: an idle ring means a shallow dispatch queue, so the pow2 pad
  floor drops to ``BIFROMQ_PIPELINE_MIN_BATCH`` (default 8) to cut
  time-to-first-result; a busy ring keeps the throughput floor (16).

The ring deliberately has NO asyncio primitives bound at construction
(no Semaphore/Event): matchers outlive event loops in tests and
multi-loop processes, so waiters are plain per-call futures created on
whatever loop is running the dispatch.
"""

from __future__ import annotations

import asyncio
import os
from collections import deque
from typing import Deque, Optional


def pipeline_enabled() -> bool:
    """Kill-switch for the async dispatch path (``BIFROMQ_PIPELINE=0``
    degrades ``match_batch_async`` to the sync serving path)."""
    return os.environ.get("BIFROMQ_PIPELINE", "1").lower() \
        not in ("0", "off", "false")


def pipeline_depth() -> int:
    """In-flight device batches (2 = double-buffered, 3 = triple)."""
    try:
        d = int(os.environ.get("BIFROMQ_PIPELINE_DEPTH", "2"))
    except ValueError:
        d = 2
    return max(1, min(d, 8))


def pipeline_min_floor() -> int:
    """Shallow-queue pow2 pad floor (the latency floor; 16 stays the
    throughput floor). Each extra floor is one more XLA shape class, so
    it is a single knob, not a free sweep."""
    try:
        f = int(os.environ.get("BIFROMQ_PIPELINE_MIN_BATCH", "8"))
    except ValueError:
        f = 8
    return max(1, min(f, 16))


def donation_enabled() -> bool:
    """Donate in-flight probe buffers to XLA (``walk_routes_donated``).
    Default on — the ring never re-reads a dispatched Probes object (the
    escalation/readback paths only touch the host TokenizedTopics copy)."""
    return os.environ.get("BIFROMQ_DONATE_BUFFERS", "1").lower() \
        not in ("0", "off", "false")


class DispatchRing:
    """Bounded in-flight dispatch slots + the queue-depth signal.

    One per TpuMatcher (created lazily on the first async match). The
    gauge surface (obs/device.py) reads ``in_flight`` / ``waiters`` /
    ``depth`` weakly; ``effective_floor`` feeds the adaptive pow2 pad.
    """

    def __init__(self, depth: Optional[int] = None,
                 min_floor: Optional[int] = None,
                 base_floor: int = 16) -> None:
        self.depth = depth if depth is not None else pipeline_depth()
        self.min_floor = (min_floor if min_floor is not None
                          else pipeline_min_floor())
        self.base_floor = base_floor
        self._inflight = 0
        self._waiters: Deque[asyncio.Future] = deque()
        # observability (tests assert overlap through these)
        self.dispatched_total = 0
        self.peak_inflight = 0

    # ---------------- slot management --------------------------------------

    @property
    def in_flight(self) -> int:
        return self._inflight

    @property
    def waiting(self) -> int:
        return len(self._waiters)

    async def acquire(self) -> None:
        while self._inflight >= self.depth:
            fut = asyncio.get_running_loop().create_future()
            self._waiters.append(fut)
            try:
                await fut
            except BaseException:
                # cancellation hygiene: a parked waiter withdraws itself
                # (a cancelled future is done(), so it must be REMOVED —
                # a stale entry would overcount ring.waiting and pin
                # effective_floor at the throughput floor); a waiter that
                # was already granted a wake but dies before using it
                # passes the wake on so the slot isn't lost
                if fut in self._waiters:
                    self._waiters.remove(fut)
                elif fut.done() and not fut.cancelled():
                    self._wake_one()
                raise
        self._inflight += 1
        self.dispatched_total += 1
        self.peak_inflight = max(self.peak_inflight, self._inflight)

    def _wake_one(self) -> None:
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(None)
                break

    def release(self) -> None:
        self._inflight = max(0, self._inflight - 1)
        self._wake_one()

    # ---------------- adaptive pad floor ------------------------------------

    def effective_floor(self) -> int:
        """Shallow queue (nothing else in flight, nobody parked) ⇒ the
        small latency floor; any concurrency ⇒ the throughput floor.

        Called AFTER acquire, so ``in_flight`` counts this dispatch too:
        1 in flight and no waiters is the idle-broker single-publish
        shape the latency floor exists for.
        """
        if self._inflight <= 1 and not self._waiters:
            return self.min_floor
        return self.base_floor

    # ---------------- fetch-on-ready ----------------------------------------

    @staticmethod
    def start_fetch(res) -> None:
        """Kick the device→host copy without blocking (fetch-on-ready
        half 1); ``np.asarray`` later finds the bytes already local.
        Only the leaves ``_fetch_walk`` actually reads — ``n_routes`` is
        derivable from ``count`` and never fetched, so copying it would
        be one wasted D2H transfer per batch on the tunnel backend."""
        for leaf in (res.start, res.count, res.overflow):
            copy_async = getattr(leaf, "copy_to_host_async", None)
            if copy_async is not None:
                try:
                    copy_async()
                except Exception:  # noqa: BLE001 — backend-optional fast path
                    return

    @staticmethod
    async def wait_ready(res, poll_s: float = 0.0005,
                         spin_polls: int = 50) -> None:
        """Yield the event loop until every result leaf is ready (half 2).

        ``is_ready`` is a PJRT-buffer query, not a sync: other coroutines
        (the NEXT batch's tokenize + dispatch) run between polls. Backends
        whose arrays lack ``is_ready`` fall through to the blocking fetch
        the caller performs next — still correct, just unoverlapped.

        Two-phase poll: the first ``spin_polls`` misses use ``sleep(0)``
        — a bare loop yield costing microseconds, which sub-millisecond
        CPU walks finish within (a timed sleep would quantize to the
        loop's ~1ms timer and tax every fast batch) — then back off to
        ``poll_s`` timed sleeps for genuinely long completions (the axon
        tunnel's ~70ms RTT), where spinning would burn a core for nothing.
        """
        leaves = [res.start, res.count, res.overflow]
        polls = 0
        while True:
            try:
                if all(leaf.is_ready() for leaf in leaves):
                    return
            except AttributeError:
                return
            await asyncio.sleep(0 if polls < spin_polls else poll_s)
            polls += 1
