"""Async device-dispatch ring (ISSUE 6 tentpole part 1).

BENCH_r01 measured the sync serving path at p50 ≈ 666ms per batch: every
publish paid `batcher queue → pow2 pad → device dispatch → BLOCKING
device_get` with nothing overlapped. This module is the overlap plane:

- a **dispatch ring** bounds the number of in-flight device batches
  (``BIFROMQ_PIPELINE_DEPTH``, default 2 = double-buffered; 3 = triple):
  batch N+1 tokenizes and enqueues on device while batch N is still
  walking, because the await happens on *readiness*, not inside dispatch;
- results come back via **fetch-on-ready**: the dispatch starts a
  ``copy_to_host_async`` immediately, the serving coroutine polls
  ``jax.Array.is_ready`` (yielding the event loop between polls — other
  batches dispatch in those gaps) and only then pays the final host copy;
- the ring's occupancy is the **queue-depth signal** for adaptive batch
  shaping: an idle ring means a shallow dispatch queue, so the pow2 pad
  floor drops to ``BIFROMQ_PIPELINE_MIN_BATCH`` (default 8) to cut
  time-to-first-result; a busy ring keeps the throughput floor (16).

The ring deliberately has NO asyncio primitives bound at construction
(no Semaphore/Event): matchers outlive event loops in tests and
multi-loop processes, so waiters are plain per-call futures created on
whatever loop is running the dispatch.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

from ..resilience.device import (BoundedSlots, BufferQuarantine,
                                 DeviceTimeoutError, device_deadline_s)
from ..utils.env import env_bool, env_int


def pipeline_enabled() -> bool:
    """Kill-switch for the async dispatch path (``BIFROMQ_PIPELINE=0``
    degrades ``match_batch_async`` to the sync serving path)."""
    return env_bool("BIFROMQ_PIPELINE", True)


def pipeline_depth() -> int:
    """In-flight device batches (2 = double-buffered, 3 = triple)."""
    return max(1, min(env_int("BIFROMQ_PIPELINE_DEPTH", 2), 8))


def pipeline_min_floor() -> int:
    """Shallow-queue pow2 pad floor (the latency floor; 16 stays the
    throughput floor). Each extra floor is one more XLA shape class, so
    it is a single knob, not a free sweep."""
    return max(1, min(env_int("BIFROMQ_PIPELINE_MIN_BATCH", 8), 16))


def donation_enabled() -> bool:
    """Donate in-flight probe buffers to XLA (``walk_routes_donated``).
    Default on — the ring never re-reads a dispatched Probes object (the
    escalation/readback paths only touch the host TokenizedTopics copy)."""
    return env_bool("BIFROMQ_DONATE_BUFFERS", True)


class DispatchRing(BoundedSlots):
    """Bounded in-flight dispatch slots + the queue-depth signal.

    One per TpuMatcher (created lazily on the first async match). The
    gauge surface (obs/device.py) reads ``in_flight`` / ``waiters`` /
    ``depth`` weakly; ``effective_floor`` feeds the adaptive pow2 pad.
    Slot admission (bound, parked-waiter futures, cancellation hygiene)
    is the shared :class:`~bifromq_tpu.resilience.device.BoundedSlots`
    machinery — the same core that gates QoS>0 ingest.
    """

    def __init__(self, depth: Optional[int] = None,
                 min_floor: Optional[int] = None,
                 base_floor: int = 16) -> None:
        super().__init__(depth if depth is not None else pipeline_depth())
        self.min_floor = (min_floor if min_floor is not None
                          else pipeline_min_floor())
        self.base_floor = base_floor
        # observability (tests assert overlap through these)
        self.dispatched_total = 0
        # ISSUE 7: timed-out slots park their orphaned result arrays here
        # until the device actually finishes with them — a reclaimed slot
        # must never let donated buffers be reused mid-flight
        self.quarantine = BufferQuarantine()
        self.timeouts_total = 0
        # ISSUE 11: stage-1 prep (tokenize + probe upload) runs BEFORE
        # ring admission for overlap, so prep tickets — not ring slots —
        # bound the probe batches resident on device. A ticket is held
        # for the whole prep + slot tenure (released WITH the slot), so
        # prepped + in-flight batches together never exceed depth + 1:
        # with the ring full, exactly ONE caller can hold an uploaded-
        # but-undispatched probe set, which is the "+1 prep-ahead" the
        # capacity model counts (obs/capacity.inflight_bytes). Without
        # the gate, K parked callers would each hold an upload the
        # model never saw.
        self._prep = BoundedSlots(self.capacity + 1)

    # ---------------- slot management --------------------------------------

    @property
    def depth(self) -> int:
        return self.capacity

    @depth.setter
    def depth(self, v: int) -> None:
        self.capacity = v
        self._prep.capacity = max(1, v + 1)

    async def acquire_prep(self) -> None:
        """Admit one stage-1 prep (see ``_prep``): held across tokenize
        + probe upload + ring admission + the walk's slot tenure,
        released together with the slot (or when the leg dies)."""
        await self._prep.acquire()

    def release_prep(self) -> None:
        self._prep.release()

    @property
    def prepping(self) -> int:
        return self._prep.in_flight

    async def acquire(self) -> None:
        await super().acquire()
        self.dispatched_total += 1

    def release(self) -> None:
        super().release()
        # opportunistic quarantine sweep: O(1) when nothing is parked
        if len(self.quarantine):
            self.quarantine.sweep()

    def reclaim(self, res, tag: Optional[str] = None) -> None:
        """A slot timed out: park its (possibly donated-aliasing) result
        arrays in quarantine until the device reports them ready. The
        caller releases the slot itself — the ring stays bounded AND
        live, instead of one stuck dispatch wedging a slot forever.
        ``tag`` attributes the parked batch (ISSUE 15: the mesh tags the
        implicated shard)."""
        self.timeouts_total += 1
        self.quarantine.add(res, tag=tag)

    async def wait_idle(self, timeout_s: float = 2.0,
                        poll_s: float = 0.002) -> bool:
        """Graceful drain (ISSUE 7): wait bounded for every in-flight
        slot to retire. Returns False on timeout — the caller proceeds
        with shutdown/compaction anyway (in-flight coroutines release
        their slots when cancelled)."""
        deadline = time.monotonic() + timeout_s
        while self._inflight > 0:
            if time.monotonic() >= deadline:
                return False
            await asyncio.sleep(poll_s)
        return True

    # ---------------- adaptive pad floor ------------------------------------

    def effective_floor(self, *, pre_acquire: bool = False) -> int:
        """Shallow queue (nothing else in flight, nobody parked) ⇒ the
        small latency floor; any concurrency ⇒ the throughput floor.

        ONE definition for both call shapes: post-acquire (the default;
        ``in_flight`` counts this dispatch too, so <=1 is the
        idle-broker single-publish shape) and ``pre_acquire`` (ISSUE 11:
        stage-1 prep chooses the pad floor BEFORE a slot is held, where
        the same idle state reads ==0).
        """
        own = 0 if pre_acquire else 1
        if self._inflight <= own and not self._waiters:
            return self.min_floor
        return self.base_floor

    def planned_floor(self) -> int:
        """The pre-admission floor the async prep leg uses."""
        return self.effective_floor(pre_acquire=True)

    # ---------------- fetch-on-ready ----------------------------------------

    @staticmethod
    def start_fetch(res) -> None:
        """Kick the device→host copy without blocking (fetch-on-ready
        half 1); ``np.asarray`` later finds the bytes already local.
        Only the leaves ``_fetch_walk`` actually reads — ``n_routes`` is
        derivable from ``count`` and never fetched, so copying it would
        be one wasted D2H transfer per batch on the tunnel backend.
        ISSUE 19 device-expand results name their own fetch set
        (``ready_leaves``): the compact pair buffers, never the grids."""
        ready = getattr(res, "ready_leaves", None)
        leaves = ready() if ready is not None \
            else (res.start, res.count, res.overflow)
        for leaf in leaves:
            copy_async = getattr(leaf, "copy_to_host_async", None)
            if copy_async is not None:
                try:
                    copy_async()
                except Exception:  # noqa: BLE001 — backend-optional fast path
                    return

    @staticmethod
    async def wait_ready(res, poll_s: float = 0.0005,
                         spin_polls: int = 50,
                         deadline_s: Optional[float] = None,
                         fault=None) -> None:
        """Yield the event loop until every result leaf is ready (half 2).

        ``is_ready`` is a PJRT-buffer query, not a sync: other coroutines
        (the NEXT batch's tokenize + dispatch) run between polls. Backends
        whose arrays lack ``is_ready`` fall through to the blocking fetch
        the caller performs next — still correct, just unoverlapped.

        Two-phase poll: the first ``spin_polls`` misses use ``sleep(0)``
        — a bare loop yield costing microseconds, which sub-millisecond
        CPU walks finish within (a timed sleep would quantize to the
        loop's ~1ms timer and tax every fast batch) — then back off to
        ``poll_s`` timed sleeps for genuinely long completions (the axon
        tunnel's ~70ms RTT), where spinning would burn a core for nothing.

        ISSUE 7 watchdog: past ``deadline_s`` (default derived from the
        dispatch-stage p99, env ``BIFROMQ_DEVICE_DEADLINE_S``) a
        :class:`DeviceTimeoutError` fires so one hung dispatch cannot
        wedge a ring slot forever. The deadline check is one monotonic
        read per poll — the sub-ms spin phase stays spin (no timed sleep
        is ever added to it). ``fault`` is a fired device FaultRule
        (models/matcher threads it from the dispatch hook): ``hang``
        withholds readiness while the rule stays installed, ``slow``
        withholds it for the rule's delay, ``flaky_ready`` makes each
        poll lie with the rule's probability.
        """
        if deadline_s is None:
            deadline_s = device_deadline_s()
        t0 = time.monotonic()
        ready = getattr(res, "ready_leaves", None)
        leaves = list(ready()) if ready is not None \
            else [res.start, res.count, res.overflow]
        polls = 0
        injector = None
        if fault is not None:
            from ..resilience.faults import get_injector
            injector = get_injector()
        while True:
            faulted = False
            if fault is not None:
                if fault.action == "hang":
                    faulted = injector.rule_active(fault)
                elif fault.action == "slow":
                    faulted = time.monotonic() - t0 < fault.delay
                elif fault.action == "flaky_ready":
                    # the documented contract is delayed-never-denied:
                    # clamp the per-poll lie below 1.0 so a rule with the
                    # default probability (1.0) stays a flake, not a hang
                    # (hang is its own action)
                    faulted = (injector.rule_active(fault)
                               and injector.rng.random()
                               < min(fault.probability, 0.95))
            if not faulted:
                try:
                    if all(leaf.is_ready() for leaf in leaves):
                        return
                except AttributeError:
                    return
            if (deadline_s is not None
                    and time.monotonic() - t0 >= deadline_s):
                raise DeviceTimeoutError(deadline_s)
            await asyncio.sleep(0 if polls < spin_polls else poll_s)
            polls += 1
