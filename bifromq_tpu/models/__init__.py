"""bifromq_tpu.models."""
