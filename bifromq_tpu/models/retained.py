"""Retained-topic index: host authority + compiled trie for filter probes.

Host-side counterpart of ops.retained (the reference's RetainTopicIndex,
bifromq-retain .../store/index/RetainTopicIndex.java:35, rebuilt from KV on
reset — here rebuilt/compiled from the authoritative per-tenant topic maps).
The oracle-grade fallback ``match_filter_host`` mirrors RetainMatcher.java:36
semantics plus the [MQTT-4.7.2-1] root-'$' rule.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..types import RouteMatcher, RouteMatcherType
from ..utils import topic as topic_util
from .automaton import (CompiledTrie, compile_tries, tokenize_filters)
from .oracle import Route, SubscriptionTrie, _TrieNode


def _topic_route(topic_levels: Sequence[str], topic_str: str) -> Route:
    """A retained topic stored as a wildcard-free 'route'; receiver == topic."""
    return Route(
        matcher=RouteMatcher(type=RouteMatcherType.NORMAL,
                             filter_levels=tuple(topic_levels),
                             mqtt_topic_filter=topic_str),
        broker_id=0, receiver_id=topic_str, deliverer_key="")


def match_filter_host(trie: SubscriptionTrie,
                      filter_levels: Sequence[str],
                      limit: Optional[int] = None) -> List[str]:
    """Exact filter-over-topic-trie match (host fallback & test oracle).

    ``limit`` makes the walk scan-bounded (early exit once ``limit``
    topics are collected — the RetainMessageMatchLimit contract): the
    production serving path always passes it. DEPTH-first traversal so a
    bounded lookup costs ~O(limit × depth) even for '+'-heavy filters
    over a million-topic trie — the level-synchronous frontier expansion
    paid the whole '+' fan-out before emitting anything (measured ~10ms
    per fallback at limit=10 on a 200K-topic trie; DFS is ~free).
    """
    out: List[str] = []
    cap = limit if limit is not None else (1 << 62)
    if cap <= 0:
        return out
    n_levels = len(filter_levels)

    class _Full(Exception):
        pass

    def emit(r) -> None:
        out.append(r.receiver_id)
        if len(out) >= cap:
            raise _Full()

    def collect_subtree(node: _TrieNode, skip_sys: bool) -> None:
        for r in node.routes.values():
            emit(r)
        for level, child in node.children.items():
            if skip_sys and level.startswith(topic_util.SYS_PREFIX):
                continue
            collect_subtree(child, False)

    def walk(node: _TrieNode, i: int) -> None:
        if i == n_levels:
            for r in node.routes.values():
                emit(r)
            return
        lvl = filter_levels[i]
        at_root = i == 0
        if lvl == topic_util.MULTI_WILDCARD:
            collect_subtree(node, skip_sys=at_root)
        elif lvl == topic_util.SINGLE_WILDCARD:
            for name, child in node.children.items():
                if at_root and name.startswith(topic_util.SYS_PREFIX):
                    continue
                walk(child, i + 1)
        else:
            child = node.children.get(lvl)
            if child is not None:
                walk(child, i + 1)

    try:
        walk(trie._root, 0)
    except _Full:
        pass
    return out


class RetainedIndex:
    """Per-tenant retained-topic tries + compiled automaton for device probes.

    Mirrors TpuMatcher's mutate-dirty-recompile contract; query side takes
    wildcard FILTERS (ops.retained walk) instead of topics.
    """

    def __init__(self, *, max_levels: int = 16, k_states: int = 32,
                 probe_len: int = 16, device=None) -> None:
        self.max_levels = max_levels
        self.k_states = k_states
        self.probe_len = probe_len
        self.device = device
        self.tries: Dict[str, SubscriptionTrie] = {}
        self._compiled: Optional[CompiledTrie] = None
        self._device_trie = None
        self._dirty = True

    def add_topic(self, tenant_id: str, topic_levels: Sequence[str],
                  topic_str: str) -> bool:
        trie = self.tries.setdefault(tenant_id, SubscriptionTrie())
        added = trie.add(_topic_route(topic_levels, topic_str))
        if added:  # payload replacement leaves the index unchanged
            self._dirty = True
        return added

    def remove_topic(self, tenant_id: str, topic_levels: Sequence[str],
                     topic_str: str) -> bool:
        trie = self.tries.get(tenant_id)
        if trie is None:
            return False
        r = _topic_route(topic_levels, topic_str)
        removed = trie.remove(r.matcher, r.receiver_url)
        if removed:
            if len(trie) == 0:
                del self.tries[tenant_id]
            self._dirty = True
        return removed

    def topic_count(self, tenant_id: str) -> int:
        trie = self.tries.get(tenant_id)
        return len(trie) if trie is not None else 0

    def refresh(self) -> CompiledTrie:
        if self._dirty or self._compiled is None:
            self._compiled = compile_tries(self.tries,
                                           max_levels=self.max_levels,
                                           probe_len=self.probe_len)
            from ..ops.match import DeviceTrie
            self._device_trie = DeviceTrie.from_compiled(self._compiled,
                                                         device=self.device)
            # slot -> retained topic string, as one object ndarray so slot
            # ranges expand with a single vectorized fancy-index instead of
            # per-slot Python (the range loop measured ~90 filters/s on the
            # c4 bench; vectorized expansion is ~3 orders faster)
            self._receiver_arr = np.array(
                [m.receiver_id for m in self._compiled.matchings],
                dtype=object)
            self._dirty = False
        return self._compiled

    def device_probes(self, queries: Sequence[Tuple[str, Sequence[str]]],
                      *, batch: Optional[int] = None):
        """Tokenize (tenant, filter_levels) pairs into device filter probes.

        Returns (probes, roots, lengths) — lengths is the host-side
        per-row level count (-1 = over-deep padding row needing host
        fallback). The ONE probe-construction definition — match_batch and
        the benchmark both use it, so they can never desynchronize."""
        from ..ops.retained import FilterProbes

        from .matcher import _pow2_batch

        ct = self.refresh()
        if batch is None:
            batch = _pow2_batch(len(queries))
        roots = [ct.root_of(t) for t, _ in queries]
        tok = tokenize_filters([f for _, f in queries], roots,
                               max_levels=ct.max_levels, salt=ct.salt,
                               batch=batch)
        return (FilterProbes.from_tokenized(tok, device=self.device),
                roots, tok.lengths)

    def walk_device(self, probes, *, k_states: Optional[int] = None):
        """Dispatch the retained walk on the current compiled tables."""
        from ..ops.retained import retained_walk

        ct = self.refresh()
        return retained_walk(self._device_trie, probes,
                             probe_len=ct.probe_len,
                             k_states=k_states or self.k_states)

    def match_batch(self, queries: Sequence[Tuple[str, Sequence[str]]],
                    *, batch: Optional[int] = None,
                    limit: Optional[int] = None) -> List[List[str]]:
        """(tenant, filter_levels) pairs → matched retained topic strings.

        ``limit`` bounds expansion per query (scan-bounded like the
        reference's RetainMessageMatchLimit): expired entries filtered by the
        caller may reduce the final result below the limit.
        """
        if not queries:
            return []
        probes, roots, lengths = self.device_probes(queries, batch=batch)
        ranges, overflow = self.walk_device(probes)
        nq = len(queries)
        ranges = np.asarray(ranges)[:nq]            # [Q, R, 2]
        # writable copy: escalation clears rescued rows in place (a bare
        # asarray view of a jax buffer is read-only)
        overflow = np.array(overflow)[:nq]
        lengths = np.asarray(lengths)[:nq]
        roots_a = np.asarray(roots[:nq])

        # native escalation: rows whose '+'-expansion outgrew the device
        # lane budget resolve EXACTLY via the C++ DFS over the same
        # compiled tables (native/retainedwalk.cpp — no lane concept, no
        # extra XLA compile; ~two orders faster than the Python oracle,
        # which stays as the last-resort fallback when the range budget
        # blows or no compiler exists)
        esc = np.nonzero(overflow & (lengths >= 0)
                         & (roots_a >= 0))[0]
        native_map: Dict[int, np.ndarray] = {}
        if esc.size:
            try:
                from .native_retained import match_rows_native
                ct = self._compiled
                sub_tok = tokenize_filters(
                    [list(queries[i][1]) for i in esc],
                    [int(roots_a[i]) for i in esc],
                    max_levels=ct.max_levels, salt=ct.salt)
                rr, rn, rovf = match_rows_native(
                    ct, sub_tok.tok_h1, sub_tok.tok_h2, sub_tok.tok_kind,
                    sub_tok.lengths, sub_tok.roots, limit=limit)
                for j, qi in enumerate(esc):
                    if not rovf[j]:
                        n = int(rn[j])
                        s0 = rr[j, :n, 0].astype(np.int64)
                        c0 = np.maximum(rr[j, :n, 1], 0).astype(np.int64)
                        if limit is not None and n:
                            cum = np.cumsum(c0)
                            c0 = np.clip(limit - (cum - c0), 0, c0)
                        native_map[int(qi)] = (s0, c0)
                        overflow[qi] = False
            except Exception:  # noqa: BLE001 — no compiler / load failure:
                pass    # rows stay on the (exact) oracle path

        starts = ranges[..., 0].astype(np.int64)
        counts = np.maximum(ranges[..., 1], 0).astype(np.int64)
        host_rows = overflow | (lengths < 0)
        counts[host_rows | (roots_a < 0)] = 0   # row mask: no device expansion
        for qi in native_map:
            counts[qi] = 0      # grid contributes nothing for native rows
        if limit is not None:
            # clip each query's ranges so the cumulative expansion stops
            # at the cap (scan-bounded like RetainMessageMatchLimit)
            cum = np.cumsum(counts, axis=1)
            counts = np.clip(limit - (cum - counts), 0, counts)
        fc = counts.ravel()
        total = int(fc.sum())
        if total:
            # ragged arange: one flat slot-index vector for the whole batch
            offs = np.cumsum(fc) - fc
            flat = (np.arange(total, dtype=np.int64)
                    - np.repeat(offs, fc) + np.repeat(starts.ravel(), fc))
            recv = self._receiver_arr[flat]
        else:
            recv = np.empty(0, dtype=object)
        chunks = np.split(recv, np.cumsum(counts.sum(axis=1))[:-1])

        out: List[List[str]] = []
        for qi, (tenant_id, levels) in enumerate(queries):
            if roots_a[qi] < 0:
                out.append([])
            elif qi in native_map:
                s0, c0 = native_map[qi]
                tot = int(c0.sum())
                if tot:
                    o = np.cumsum(c0) - c0
                    flat = (np.arange(tot, dtype=np.int64)
                            - np.repeat(o, c0) + np.repeat(s0, c0))
                    out.append(list(self._receiver_arr[flat]))
                else:
                    out.append([])
            elif host_rows[qi]:
                out.append(match_filter_host(self.tries[tenant_id],
                                             list(levels), limit=limit))
            else:
                out.append(list(chunks[qi]))
        return out

    def match(self, tenant_id: str, filter_levels: Sequence[str],
              limit: Optional[int] = None) -> List[str]:
        return self.match_batch([(tenant_id, filter_levels)], limit=limit)[0]
