"""Retained-topic index: host authority + compiled trie for filter probes.

Host-side counterpart of ops.retained (the reference's RetainTopicIndex,
bifromq-retain .../store/index/RetainTopicIndex.java:35, rebuilt from KV on
reset — here rebuilt/compiled from the authoritative per-tenant topic maps).
The oracle-grade fallback ``match_filter_host`` mirrors RetainMatcher.java:36
semantics plus the [MQTT-4.7.2-1] root-'$' rule.

ISSUE 13: the index is PATCHED, not rebuilt, on the mutation path —
RETAIN set/clear/expire fold into the live
:class:`~bifromq_tpu.retained_plane.patched.RetainedPatchableTrie`
arenas as in-place row writes (tombstones, resurrections, extras-plane
appends, child-run maintenance) shipped to device as narrow scatters;
``compile_tries`` survives only for the first build, reset-from-KV, and
fragmentation-triggered compaction. The scan side is staged
(prepare → dispatch → fetch → expand) so the async serving plane
(retained_plane/scan.py) can thread the shared dispatch-ring/breaker/
watchdog machinery between the stages exactly like the forward matcher.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..types import RouteMatcher, RouteMatcherType
from ..utils import topic as topic_util
from ..utils.env import env_bool
from .automaton import (CompiledTrie, PatchFallback, _next_pow2,
                        compile_tries, tokenize_filters)
from .oracle import Route, SubscriptionTrie, _TrieNode


def retained_patch_enabled() -> bool:
    """Kill-switch for the in-place retained patch plane
    (``BIFROMQ_RETAIN_PATCH=0`` restores the rebuild-on-mutation path)."""
    from .automaton import patch_enabled
    return patch_enabled() and env_bool("BIFROMQ_RETAIN_PATCH", True)


def _topic_route(topic_levels: Sequence[str], topic_str: str) -> Route:
    """A retained topic stored as a wildcard-free 'route'; receiver == topic."""
    return Route(
        matcher=RouteMatcher(type=RouteMatcherType.NORMAL,
                             filter_levels=tuple(topic_levels),
                             mqtt_topic_filter=topic_str),
        broker_id=0, receiver_id=topic_str, deliverer_key="")


def match_filter_host(trie: SubscriptionTrie,
                      filter_levels: Sequence[str],
                      limit: Optional[int] = None) -> List[str]:
    """Exact filter-over-topic-trie match (host fallback & test oracle).

    ``limit`` makes the walk scan-bounded (early exit once ``limit``
    topics are collected — the RetainMessageMatchLimit contract): the
    production serving path always passes it. DEPTH-first traversal so a
    bounded lookup costs ~O(limit × depth) even for '+'-heavy filters
    over a million-topic trie — the level-synchronous frontier expansion
    paid the whole '+' fan-out before emitting anything (measured ~10ms
    per fallback at limit=10 on a 200K-topic trie; DFS is ~free).
    """
    out: List[str] = []
    cap = limit if limit is not None else (1 << 62)
    if cap <= 0:
        return out
    n_levels = len(filter_levels)

    class _Full(Exception):
        pass

    def emit(r) -> None:
        out.append(r.receiver_id)
        if len(out) >= cap:
            raise _Full()

    def collect_subtree(node: _TrieNode, skip_sys: bool) -> None:
        for r in node.routes.values():
            emit(r)
        for level, child in node.children.items():
            if skip_sys and level.startswith(topic_util.SYS_PREFIX):
                continue
            collect_subtree(child, False)

    def walk(node: _TrieNode, i: int) -> None:
        if i == n_levels:
            for r in node.routes.values():
                emit(r)
            return
        lvl = filter_levels[i]
        at_root = i == 0
        if lvl == topic_util.MULTI_WILDCARD:
            collect_subtree(node, skip_sys=at_root)
        elif lvl == topic_util.SINGLE_WILDCARD:
            for name, child in node.children.items():
                if at_root and name.startswith(topic_util.SYS_PREFIX):
                    continue
                walk(child, i + 1)
        else:
            child = node.children.get(lvl)
            if child is not None:
                walk(child, i + 1)

    try:
        walk(trie._root, 0)
    except _Full:
        pass
    return out


class _ScanPrep:
    """Stage-0 output of the retained scan pipeline: tokenized +
    uploaded filter probes plus the host mirrors the expansion needs.
    ``ct``/``recv`` are the SNAPSHOT the walk dispatched against — the
    matcher's _InFlight discipline: a compaction swapping the compiled
    base mid-flight (the async leg genuinely awaits between dispatch
    and expand) must not let old slot ids index a renumbered world."""

    __slots__ = ("queries", "probes", "roots", "lengths", "batch", "ct",
                 "recv")

    def __init__(self, **kw) -> None:
        for k, v in kw.items():
            setattr(self, k, v)


class RetainedIndex:
    """Per-tenant retained-topic tries + compiled automaton for device probes.

    Mirrors TpuMatcher's serving contract; query side takes wildcard
    FILTERS (ops.retained walk) instead of topics. ISSUE 13: mutations
    fold into the live arenas in place (``rebuilds`` stays 0 under a
    retained flood; ``compactions`` counts the fragmentation-triggered
    folds which are the only compiles after the first build).
    """

    def __init__(self, *, max_levels: int = 16, k_states: int = 32,
                 probe_len: int = 16, device=None,
                 patched: Optional[bool] = None) -> None:
        self.max_levels = max_levels
        self.k_states = k_states
        self.probe_len = probe_len
        self.device = device
        self.tries: Dict[str, SubscriptionTrie] = {}
        self._compiled: Optional[CompiledTrie] = None
        self._device_tables = None
        self._dirty = True
        self._patched = (retained_patch_enabled() if patched is None
                         else patched)
        # observability: full compiles split by trigger — a retained
        # flood must keep `rebuilds` at ZERO (ISSUE 13 acceptance);
        # compaction is the fragmentation fallback
        self.rebuilds = 0
        self.compactions = 0
        self.compile_time_s = 0.0
        self.patch_fallbacks = 0
        self.patch_flushes = 0
        # exact-invalidation consumers (scan cache, retained delta log):
        # fired per applied mutation with (tenant, levels, op) where op
        # is "set" | "del"; a full rebuild does NOT fire (results are
        # content-identical across renumbering)
        self.delta_hooks: List = []
        # slot -> retained topic string, capacity-padded object ndarray
        # so slot ranges expand with one vectorized fancy-index (the
        # per-slot loop measured ~90 filters/s on the c4 bench)
        self._receiver_arr = np.empty(0, dtype=object)
        # the scan plane pins its dispatch ring here so EVERY flush —
        # including ring-less callers like the coproc's RO wire query —
        # sees the in-flight scans before deciding to donate
        self.serving_ring = None

    # ---------------- mutation side (patch-first, ISSUE 13) -----------------

    def _emit_delta(self, tenant_id: str, levels, op: str) -> None:
        for cb in list(self.delta_hooks):
            try:
                cb(tenant_id, tuple(levels), op)
            except Exception:  # noqa: BLE001 — observers must not break
                import logging
                logging.getLogger(__name__).exception("retained delta hook")

    def _patch_base(self):
        """The live patchable base, or None when patching cannot serve
        this mutation (no base yet / kill-switch / pending rebuild)."""
        if not self._patched or self._dirty or self._compiled is None:
            return None
        from ..retained_plane.patched import RetainedPatchableTrie
        ct = self._compiled
        return ct if isinstance(ct, RetainedPatchableTrie) else None

    def add_topic(self, tenant_id: str, topic_levels: Sequence[str],
                  topic_str: str) -> bool:
        trie = self.tries.setdefault(tenant_id, SubscriptionTrie())
        route = _topic_route(topic_levels, topic_str)
        added = trie.add(route)
        if added:  # payload replacement leaves the index unchanged
            base = self._patch_base()
            if base is not None:
                try:
                    action, slot = base.retained_add(
                        tenant_id, list(topic_levels), route)
                    if action == "add":
                        self._recv_set(slot, topic_str)
                except PatchFallback:
                    # patch-era hash collision (astronomically rare):
                    # never guess — the rebuild re-salts
                    self.patch_fallbacks += 1
                    self._dirty = True
            else:
                self._dirty = True
            self._emit_delta(tenant_id, topic_levels, "set")
        return added

    def remove_topic(self, tenant_id: str, topic_levels: Sequence[str],
                     topic_str: str) -> bool:
        trie = self.tries.get(tenant_id)
        if trie is None:
            return False
        r = _topic_route(topic_levels, topic_str)
        removed = trie.remove(r.matcher, r.receiver_url)
        if removed:
            if len(trie) == 0:
                del self.tries[tenant_id]
            base = self._patch_base()
            if base is not None:
                try:
                    if not base.retained_remove(tenant_id,
                                                list(topic_levels)):
                        # index/authority drift — rebuild, never serve wrong
                        self.patch_fallbacks += 1
                        self._dirty = True
                except PatchFallback:
                    self.patch_fallbacks += 1
                    self._dirty = True
            else:
                self._dirty = True
            self._emit_delta(tenant_id, topic_levels, "del")
        return removed

    def topic_count(self, tenant_id: str) -> int:
        trie = self.tries.get(tenant_id)
        return len(trie) if trie is not None else 0

    # ---------------- compile / compaction ----------------------------------

    def _recv_set(self, slot: int, topic_str: str) -> None:
        if slot >= self._receiver_arr.shape[0]:
            arr = np.empty(_next_pow2(slot + 1, floor=64), dtype=object)
            arr[:self._receiver_arr.shape[0]] = self._receiver_arr
            self._receiver_arr = arr
        self._receiver_arr[slot] = topic_str

    def frag_pending(self) -> bool:
        base = self._patch_base()
        return base is not None and base.frag_pending()

    def refresh(self) -> CompiledTrie:
        if self._compiled is None:
            reason = "first"
        elif self._dirty:
            reason = "rebuild"
        elif self.frag_pending():
            # fragmentation compaction: the ONLY compile a patched index
            # runs after its first build (tombstone/garbage reclaim)
            reason = "compact"
        else:
            return self._compiled
        t0 = time.perf_counter()
        ct = compile_tries(self.tries, max_levels=self.max_levels,
                           probe_len=self.probe_len)
        if self._patched:
            from ..retained_plane.patched import RetainedPatchableTrie
            ct = RetainedPatchableTrie(ct)
        self._compiled = ct
        from ..ops.retained import RetainedDeviceTables
        self._device_tables = RetainedDeviceTables.from_trie(
            ct, device=self.device)
        arr = np.empty(_next_pow2(max(len(ct.matchings), 1), floor=64),
                       dtype=object)
        for i, m in enumerate(ct.matchings):
            arr[i] = m.receiver_id
        self._receiver_arr = arr
        self._dirty = False
        self.compile_time_s += time.perf_counter() - t0
        if reason == "rebuild":
            self.rebuilds += 1
        elif reason == "compact":
            self.compactions += 1
        return self._compiled

    def flush_device(self, *, ring=None, own_slots: int = 0) -> None:
        """Ship pending host patches to device as narrow scatters —
        coalesced, at most one flush per dispatch. Donation only when no
        in-flight scan can still read the old tables (same proof the
        forward matcher uses: the caller's own not-yet-dispatched slot
        plus an empty quarantine)."""
        base = self._patch_base()
        if base is None or not base.dirty or self._device_tables is None:
            return
        from ..ops.retained import patch_retained_tables
        if ring is None:
            # a ring-less caller (sync path, RO query) must still honor
            # the plane's in-flight scans — donating tables a parked
            # async walk is reading is the exact use-after-donate the
            # quarantine discipline exists to prevent
            ring = self.serving_ring
            own_slots = 0
        donate = ring is None or (ring.in_flight <= own_slots
                                  and not len(ring.quarantine))
        dev, _stats = patch_retained_tables(
            self._device_tables, base, device=self.device, donate=donate)
        self._device_tables = dev
        self.patch_flushes += 1

    # ---------------- staged scan pipeline (ISSUE 13) -----------------------

    def prepare_scan(self, queries: Sequence[Tuple[str, Sequence[str]]],
                     *, batch: Optional[int] = None) -> _ScanPrep:
        """Stage 0: tokenize (tenant, filter_levels) pairs into device
        filter probes. The ONE probe-construction definition — the sync
        path, the async plane and the benchmark all use it."""
        from ..ops.retained import FilterProbes
        from .matcher import _pow2_batch

        ct = self.refresh()
        if batch is None:
            batch = _pow2_batch(len(queries))
        roots = [ct.root_of(t) for t, _ in queries]
        filters = [f for _, f in queries]
        # ISSUE 17 satellite: the filter-probe twin of the publish-side
        # byte plane — raw filter bytes ship to device, the BLAKE2b
        # kernel hashes the literal lanes there, wildcard lanes ride the
        # kind grid. Same gate and fallback contract as device_tokenize:
        # rows the kernel can't hash are padding (-1) and fall back.
        from ..ops.tokenize import (device_tokenize_enabled,
                                    device_tokenize_filters)
        if device_tokenize_enabled():
            mirror, probes = device_tokenize_filters(
                filters, roots, max_levels=ct.max_levels, salt=ct.salt,
                batch=batch, device=self.device)
            return _ScanPrep(queries=list(queries), probes=probes,
                             roots=np.asarray(roots, dtype=np.int64),
                             lengths=mirror.lengths, batch=batch, ct=ct)
        tok = tokenize_filters(filters, roots,
                               max_levels=ct.max_levels, salt=ct.salt,
                               batch=batch)
        return _ScanPrep(queries=list(queries),
                         probes=FilterProbes.from_tokenized(
                             tok, device=self.device),
                         roots=np.asarray(roots, dtype=np.int64),
                         lengths=tok.lengths, batch=batch, ct=ct)

    def device_probes(self, queries: Sequence[Tuple[str, Sequence[str]]],
                      *, batch: Optional[int] = None):
        """Back-compat probe constructor: (probes, roots, lengths)."""
        prep = self.prepare_scan(queries, batch=batch)
        return prep.probes, list(prep.roots), prep.lengths

    def dispatch_scan(self, prep: _ScanPrep, *,
                      k_states: Optional[int] = None,
                      ring=None, own_slots: int = 0):
        """Stage 1: flush pending patches, enqueue the extras-aware walk.
        Returns ``(prep, RetainedScanResult)`` — the result is ENQUEUED,
        not synchronized, and ``prep`` may be a re-prep: a compaction
        swap landing between prep and dispatch (the async leg awaits
        ring admission in the gap) renumbers roots/salt, so the probes
        re-tokenize against the installed base."""
        from ..ops.retained import retained_walk_ext
        if self._compiled is not prep.ct:
            prep = self.prepare_scan(prep.queries, batch=prep.batch)
        self.flush_device(ring=ring, own_slots=own_slots)
        # snapshot the slot→topic mirror AT dispatch: later growth
        # reallocates the array, and a later compaction renumbers slots
        # entirely — emitted ids must expand against THIS world
        prep.recv = self._receiver_arr
        res = retained_walk_ext(self._device_tables, prep.probes,
                                probe_len=prep.ct.probe_len,
                                k_states=k_states or self.k_states)
        return prep, res

    @staticmethod
    def fetch_scan(res):
        """Stage 2: the one true synchronization — writable host copies
        (escalation clears rescued rows in place)."""
        return (np.asarray(res.start), np.asarray(res.count),
                np.array(res.overflow))

    def walk_device(self, probes, *, k_states: Optional[int] = None):
        """Dispatch the retained walk on the current compiled tables
        (back-compat surface: returns (base ranges, overflow))."""
        from ..ops.retained import retained_walk_ext
        self.refresh()
        self.flush_device()
        res = retained_walk_ext(self._device_tables, probes,
                                probe_len=self._compiled.probe_len,
                                k_states=k_states or self.k_states)
        return res.start, res.overflow

    # ---------------- expansion (stage 3) -----------------------------------

    def expand_scan(self, prep: _ScanPrep, fetched,
                    limit: Optional[int] = None) -> List[List[str]]:
        """ranges → retained topic strings: native/host escalation for
        overflow rows, extras-plane resolution, dead-slot filtering, and
        scan-bounded ``limit`` trimming — all against host mirrors."""
        queries = prep.queries
        nq = len(queries)
        base_r, ext_r, overflow = fetched
        base_r = base_r[:nq]
        ext_r = ext_r[:nq]
        overflow = np.array(overflow[:nq])    # writable: escalation clears
        lengths = np.asarray(prep.lengths)[:nq]
        roots_a = prep.roots[:nq]
        # the dispatch-time snapshot, NOT the live index: a compaction
        # landing mid-flight must not renumber under this expansion
        ct = prep.ct
        recv = getattr(prep, "recv", None)
        if recv is None:
            recv = self._receiver_arr
        from ..retained_plane.patched import RetainedPatchableTrie
        base = ct if isinstance(ct, RetainedPatchableTrie) else None
        pristine = base is None or base.pristine
        kind_arr = ct.slot_kind if (base is not None
                                    and base.dead_slots) else None

        # native escalation: '+'-exploded rows resolve via the C++ DFS
        # over the same compiled tables — ONLY while the base is
        # pristine (the native walker reads the frozen subtree ranges;
        # patch-era extras/tombstones route overflow rows to the exact
        # Python oracle until the next compaction)
        native_map: Dict[int, tuple] = {}
        esc = np.nonzero(overflow & (lengths >= 0) & (roots_a >= 0))[0]
        if esc.size and pristine:
            try:
                from .native_retained import match_rows_native
                sub_tok = tokenize_filters(
                    [list(queries[i][1]) for i in esc],
                    [int(roots_a[i]) for i in esc],
                    max_levels=ct.max_levels, salt=ct.salt)
                rr, rn, rovf = match_rows_native(
                    ct, sub_tok.tok_h1, sub_tok.tok_h2, sub_tok.tok_kind,
                    sub_tok.lengths, sub_tok.roots, limit=limit)
                for j, qi in enumerate(esc):
                    if not rovf[j]:
                        n = int(rn[j])
                        s0 = rr[j, :n, 0].astype(np.int64)
                        c0 = np.maximum(rr[j, :n, 1], 0).astype(np.int64)
                        if limit is not None and n:
                            cum = np.cumsum(c0)
                            c0 = np.clip(limit - (cum - c0), 0, c0)
                        native_map[int(qi)] = (s0, c0)
                        overflow[qi] = False
            except Exception:  # noqa: BLE001 — no compiler / load failure:
                pass    # rows stay on the (exact) oracle path

        starts = base_r[..., 0].astype(np.int64)
        counts = np.maximum(base_r[..., 1], 0).astype(np.int64)
        estarts = ext_r[..., 0].astype(np.int64)
        ecounts = np.maximum(ext_r[..., 1], 0).astype(np.int64)
        host_rows = overflow | (lengths < 0)
        row_mask = host_rows | (roots_a < 0)
        counts[row_mask] = 0
        ecounts[row_mask] = 0
        for qi in native_map:
            counts[qi] = 0      # grid contributes nothing for native rows
            ecounts[qi] = 0
        if limit is not None:
            # clip the CONCATENATED base+extras counts so expansion stops
            # at the cap (scan-bounded like RetainMessageMatchLimit); a
            # base with tombstones gets dead-slot head-room, trimmed back
            # after host filtering
            cap = limit if kind_arr is None \
                else limit + base.expansion_budget()
            all_c = np.concatenate([counts, ecounts], axis=1)
            cum = np.cumsum(all_c, axis=1)
            all_c = np.clip(cap - (cum - all_c), 0, all_c)
            counts = all_c[:, :counts.shape[1]]
            ecounts = all_c[:, counts.shape[1]:]

        def _ragged(st, ct_):
            fc = ct_.ravel()
            total = int(fc.sum())
            if not total:
                return (np.empty(0, dtype=np.int64),
                        np.zeros(nq + 1, dtype=np.int64))
            offs = np.cumsum(fc) - fc
            flat = (np.arange(total, dtype=np.int64)
                    - np.repeat(offs, fc) + np.repeat(st.ravel(), fc))
            row_offs = np.concatenate(
                [np.zeros(1, np.int64), np.cumsum(ct_.sum(axis=1))])
            return flat, row_offs

        bslots, boffs = _ragged(starts, counts)
        eidx, eoffs = _ragged(estarts, ecounts)
        if eidx.size:
            extra_host = base.extra_list
            eslots = extra_host[eidx].astype(np.int64)
        else:
            eslots = eidx

        out: List[List[str]] = []
        for qi, (tenant_id, levels) in enumerate(queries):
            if roots_a[qi] < 0:
                out.append([])
                continue
            if qi in native_map:
                s0, c0 = native_map[qi]
                tot = int(c0.sum())
                if tot:
                    o = np.cumsum(c0) - c0
                    flat = (np.arange(tot, dtype=np.int64)
                            - np.repeat(o, c0) + np.repeat(s0, c0))
                    out.append(list(recv[flat]))
                else:
                    out.append([])
                continue
            if host_rows[qi]:
                trie = self.tries.get(tenant_id)
                out.append(match_filter_host(trie, list(levels),
                                             limit=limit)
                           if trie is not None else [])
                continue
            row = np.concatenate([bslots[boffs[qi]:boffs[qi + 1]],
                                  eslots[eoffs[qi]:eoffs[qi + 1]]])
            if kind_arr is not None and row.size:
                row = row[kind_arr[row] != CompiledTrie.SLOT_DEAD]
            if limit is not None and row.size > limit:
                row = row[:limit]
            out.append(list(recv[row]) if row.size else [])
        return out

    # ---------------- sync entry points -------------------------------------

    def match_batch(self, queries: Sequence[Tuple[str, Sequence[str]]],
                    *, batch: Optional[int] = None,
                    limit: Optional[int] = None) -> List[List[str]]:
        """(tenant, filter_levels) pairs → matched retained topic strings.

        ``limit`` bounds expansion per query (scan-bounded like the
        reference's RetainMessageMatchLimit): expired entries filtered by
        the caller may reduce the final result below the limit.
        """
        if not queries:
            return []
        prep = self.prepare_scan(queries, batch=batch)
        prep, res = self.dispatch_scan(prep)
        return self.expand_scan(prep, self.fetch_scan(res), limit=limit)

    def match(self, tenant_id: str, filter_levels: Sequence[str],
              limit: Optional[int] = None) -> List[str]:
        return self.match_batch([(tenant_id, filter_levels)], limit=limit)[0]
