"""Pure-Python subscription-trie matcher: the semantics oracle & CPU fallback.

This mirrors the observable behavior of the reference hot loop —
``TenantRouteMatcher.matchAll`` (bifromq-dist/bifromq-dist-worker/src/main/java/
org/apache/bifromq/dist/worker/cache/TenantRouteMatcher.java:68) joined with
the ``TopicFilterIterator`` expansion-set semantics
(bifromq-dist-coproc-proto .../trie/TopicFilterIterator.java:38) — but with an
idiomatic direct NFA walk over a level trie instead of the reference's
sort-merge join over a KV iterator (that design is RocksDB-iterator-shaped;
ours is table-shaped, see models/automaton.py for the TPU form).

Roles:
- Ground truth in parity tests for the TPU automaton walk.
- Host-side fallback for probes that overflow the fixed-shape device walk
  (mirrors the reference's seek-vs-next fallback heuristic role,
  TenantRouteMatcher.java:129-136).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Tuple

from ..types import RouteMatcher, RouteMatcherType
from ..utils import topic as topic_util


@dataclass(frozen=True)
class Route:
    """One route-table entry: a matcher plus its delivery target.

    Equivalent to a decoded dist-worker-schema record
    (bifromq-dist-worker-schema .../schema/KVSchemaUtil.java:96-130):
    normal routes carry an incarnation; shared routes live in a group map
    keyed by receiver.
    """
    matcher: RouteMatcher
    broker_id: int
    receiver_id: str
    deliverer_key: str
    incarnation: int = 0

    @property
    def receiver_url(self) -> Tuple[int, str, str]:
        return (self.broker_id, self.receiver_id, self.deliverer_key)


class _TrieNode:
    __slots__ = ("children", "routes", "groups")

    def __init__(self) -> None:
        self.children: Dict[str, _TrieNode] = {}
        # normal routes terminating at this node, keyed by receiver_url
        self.routes: Dict[Tuple[int, str, str], Route] = {}
        # shared groups keyed by (matcher type, group name): "$share/g/f" and
        # "$oshare/g/f" are distinct route groups in the reference schema
        # (distinct flag byte in the route key, KVSchemaConstants.java:25-33)
        self.groups: Dict[Tuple[int, str], Dict[Tuple[int, str, str], Route]] = {}

    def is_empty(self) -> bool:
        return not self.children and not self.routes and not self.groups


PERSISTENT_SUB_BROKER_ID = 1  # inbox sub-broker (IInboxClient.java:55 id=1)
UNCAPPED_FANOUT = 2 ** 31 - 1  # "no limit" sentinel for fan-out caps


@dataclass
class MatchedRoutes:
    """Match result with caps mirroring
    bifromq-dist-worker .../cache/MatchedRoutes.java:38 semantics:

    - ``max_persistent_fanout`` caps only *persistent* normal routes
      (sub-broker id == 1, MatchedRoutes.addNormalMatching:88-104); transient
      routes are uncapped.
    - ``max_group_fanout`` caps the number of distinct *group matchings*
      (keyed by the full mqtt topic filter incl. the share prefix,
      MatchedRoutes.putGroupMatching:119-141), not members within a group.
    """
    normal: List[Route] = field(default_factory=list)
    # mqtt_topic_filter ("$share/g/f" / "$oshare/g/f") -> member routes
    groups: Dict[str, List[Route]] = field(default_factory=dict)
    persistent_fanout: int = 0
    max_persistent_fanout_exceeded: bool = False
    max_group_fanout_exceeded: bool = False

    def all_routes(self) -> List[Route]:
        out = list(self.normal)
        for members in self.groups.values():
            out.extend(members)
        return out


class SubscriptionTrie:
    """A mutable per-tenant subscription trie with NFA wildcard matching.

    add/remove mirror DistWorkerCoProc.batchAddRoute/batchRemoveRoute effects
    on the route table (DistWorkerCoProc.java:304/415): normal routes are
    incarnation-guarded per receiver; shared routes upsert into a group map.
    """

    def __init__(self) -> None:
        self._root = _TrieNode()
        self._count = 0

    def __len__(self) -> int:
        return self._count

    def add(self, route: Route) -> bool:
        """Insert or refresh a route. Returns True if a new entry was created.

        Incarnation guard: an insert with a stale incarnation (< existing) is
        ignored, matching the reference's guard on normal-route upsert.
        """
        return self.add_effective(route)[0]

    def add_effective(self, route: Route) -> Tuple[bool, bool]:
        """Insert or refresh a route; returns (created, effective).

        ``created``: a new entry was created. ``effective``: the stored state
        changed at all (a refresh of an existing entry with an equal-or-newer
        incarnation is effective but not created; a stale-incarnation insert
        is neither). Overlay maintenance (TpuMatcher) keys off ``effective``.
        """
        url = route.receiver_url
        # probe without creating first: a stale-incarnation insert must not
        # materialize (and leak) empty trie nodes along a new path
        probe = self._root
        for level in route.matcher.filter_levels:
            probe = probe.children.get(level)
            if probe is None:
                break
        if (probe is not None
                and route.matcher.type == RouteMatcherType.NORMAL):
            existing = probe.routes.get(url)
            if existing is not None:
                if existing.incarnation > route.incarnation:
                    return False, False
                probe.routes[url] = route
                return False, True
        node = self._root
        for level in route.matcher.filter_levels:
            node = node.children.setdefault(level, _TrieNode())
        if route.matcher.type == RouteMatcherType.NORMAL:
            node.routes[url] = route
            self._count += 1
            return True, True
        gkey = (int(route.matcher.type), route.matcher.group or "")
        group = node.groups.setdefault(gkey, {})
        created = url not in group
        group[url] = route
        if created:
            self._count += 1
        return created, True

    def remove(self, matcher: RouteMatcher, receiver_url: Tuple[int, str, str],
               incarnation: int = 0) -> bool:
        """Remove a route; stale-incarnation removes of normal routes are no-ops."""
        path: List[Tuple[_TrieNode, str]] = []
        node = self._root
        for level in matcher.filter_levels:
            child = node.children.get(level)
            if child is None:
                return False
            path.append((node, level))
            node = child
        removed = False
        if matcher.type == RouteMatcherType.NORMAL:
            existing = node.routes.get(receiver_url)
            if existing is not None and existing.incarnation <= incarnation:
                del node.routes[receiver_url]
                removed = True
        else:
            gkey = (int(matcher.type), matcher.group or "")
            group = node.groups.get(gkey)
            if group is not None and receiver_url in group:
                del group[receiver_url]
                if not group:
                    del node.groups[gkey]
                removed = True
        if removed:
            self._count -= 1
            # prune empty branches
            for parent, level in reversed(path):
                child = parent.children[level]
                if child.is_empty():
                    del parent.children[level]
                else:
                    break
        return removed

    def routes(self) -> Iterable[Route]:
        stack = [self._root]
        while stack:
            n = stack.pop()
            yield from n.routes.values()
            for g in n.groups.values():
                yield from g.values()
            stack.extend(n.children.values())

    def match(self, topic_levels: List[str],
              max_persistent_fanout: int = UNCAPPED_FANOUT,
              max_group_fanout: int = UNCAPPED_FANOUT) -> MatchedRoutes:
        """NFA walk collecting every matching route.

        Semantics identical to utils.topic.matches applied to every stored
        filter, including the [MQTT-4.7.2-1] '$'-first-level rule; caps follow
        MatchedRoutes.java:38 (normal-route cap counts every normal route,
        group cap counts members per group).
        """
        out = MatchedRoutes()
        sys_first = bool(topic_levels) and topic_levels[0].startswith(topic_util.SYS_PREFIX)
        n_levels = len(topic_levels)
        # active set of (node, wildcard-blocked) — blocked only matters at level 0
        active: List[_TrieNode] = [self._root]
        for i in range(n_levels + 1):
            allow_wildcard = not (i == 0 and sys_first)
            next_active: List[_TrieNode] = []
            for node in active:
                # '#' child accepts regardless of remaining levels
                if allow_wildcard:
                    acc = node.children.get(topic_util.MULTI_WILDCARD)
                    if acc is not None:
                        self._collect(acc, out, max_persistent_fanout, max_group_fanout)
                if i == n_levels:
                    self._collect(node, out, max_persistent_fanout, max_group_fanout)
                    continue
                level = topic_levels[i]
                # literal '+'/'#' levels are invalid in topic names and can
                # only exist in the trie as wildcard children — skipping the
                # exact lookup keeps the oracle consistent with the device
                # walk even on unvalidated input
                exact = (node.children.get(level)
                         if level not in (topic_util.SINGLE_WILDCARD,
                                          topic_util.MULTI_WILDCARD) else None)
                if exact is not None:
                    next_active.append(exact)
                if allow_wildcard:
                    plus = node.children.get(topic_util.SINGLE_WILDCARD)
                    if plus is not None:
                        next_active.append(plus)
            active = next_active
            if not active and i < n_levels:
                break
        return out

    @staticmethod
    def _collect(node: _TrieNode, out: MatchedRoutes,
                 max_persistent_fanout: int, max_group_fanout: int) -> None:
        for route in node.routes.values():
            if route.broker_id == PERSISTENT_SUB_BROKER_ID:
                if out.persistent_fanout >= max_persistent_fanout:
                    out.max_persistent_fanout_exceeded = True
                    continue
                out.persistent_fanout += 1
            out.normal.append(route)
        for members in node.groups.values():
            if not members:
                continue
            key = next(iter(members.values())).matcher.mqtt_topic_filter
            if key not in out.groups and len(out.groups) >= max_group_fanout:
                out.max_group_fanout_exceeded = True
                continue
            out.groups[key] = list(members.values())
