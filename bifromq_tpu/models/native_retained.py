"""ctypes binding for the native retained-filter walker
(native/retainedwalk.cpp).

Resolves '+'-heavy filters whose frontier outgrows every device lane
budget: a C++ DFS over the compiled int32 tables emits exact slot
ranges ~two orders faster than the Python trie oracle. Parity with
match_filter_host is enforced by tests/test_retained.py.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

import numpy as np

from ..utils.nativelib import compile_and_load

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native",
    "retainedwalk.cpp")
_SO = os.path.join(os.path.dirname(_SRC), "libretainedwalk.so")


def load_lib():
    """Raises RuntimeError (cached) when the toolchain is unavailable."""
    lib = compile_and_load(_SRC, _SO)
    if not getattr(lib, "_rw_typed", False):
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64 = ctypes.c_int64
        lib.retained_match_rows.argtypes = [
            i32p, i32p, i64, i64, i32p,
            i32p, i32p, i32p, i32p, i32p,
            i64, i64, i64, i64,
            i32p, i32p, u8p,
        ]
        lib._rw_typed = True
    return lib


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def match_rows_native(ct, tok_h1: np.ndarray, tok_h2: np.ndarray,
                      tok_kind: np.ndarray, lengths: np.ndarray,
                      roots: np.ndarray, *, max_ranges: int = 8192,
                      limit: Optional[int] = None
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Walk tokenized filter rows against ``ct``'s compiled tables.

    Returns (ranges [R, max_ranges, 2] int32, n_ranges [R] int32,
    overflow [R] bool) — overflow means the range budget blew and the
    caller must fall back to the oracle for that row.
    """
    lib = load_lib()
    tok_h1 = np.ascontiguousarray(tok_h1, dtype=np.int32)
    tok_h2 = np.ascontiguousarray(tok_h2, dtype=np.int32)
    tok_kind = np.ascontiguousarray(tok_kind, dtype=np.int32)
    lengths = np.ascontiguousarray(lengths, dtype=np.int32)
    roots = np.ascontiguousarray(roots, dtype=np.int32)
    node_tab = np.ascontiguousarray(ct.node_tab, dtype=np.int32)
    edge_tab = np.ascontiguousarray(ct.edge_tab, dtype=np.int32)
    child_list = np.ascontiguousarray(ct.child_list, dtype=np.int32)
    n_rows, width = tok_h1.shape
    out_ranges = np.zeros((n_rows, max_ranges, 2), dtype=np.int32)
    out_n = np.zeros(n_rows, dtype=np.int32)
    out_ovf = np.zeros(n_rows, dtype=np.uint8)
    lib.retained_match_rows(
        _i32p(node_tab), _i32p(edge_tab),
        ctypes.c_int64(edge_tab.shape[0]),
        ctypes.c_int64(edge_tab.shape[1]), _i32p(child_list),
        _i32p(tok_h1), _i32p(tok_h2), _i32p(tok_kind),
        _i32p(lengths), _i32p(roots),
        ctypes.c_int64(n_rows), ctypes.c_int64(width),
        ctypes.c_int64(max_ranges),
        ctypes.c_int64(limit if limit is not None else 0),
        _i32p(out_ranges), _i32p(out_n),
        out_ovf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return out_ranges, out_n, out_ovf.astype(bool)
