"""HTTP management API (≈ bifromq-apiserver).

Reference endpoints (apiserver/http/handler/*: PubHandler.java:62 et al.):
pub / sub / unsub / kill / expire-sessions / retain ops + cluster
introspection. Here a dependency-free asyncio HTTP/1.1 server exposing:

  PUT  /pub?tenant_id=&topic=&qos=&retain=     body = payload
  PUT  /sub?tenant_id=&client_id=&topic_filter=&qos=
  DELETE /unsub?tenant_id=&client_id=&topic_filter=
  DELETE /kill?tenant_id=&client_id=
  DELETE /session?tenant_id=&client_id=         (expire/delete inbox)
  PUT  /retain?tenant_id=&topic=                body = payload ('' clears)
  GET  /cluster                                  (gossip membership, if any)
  GET  /sessions?tenant_id=
  GET  /routes?tenant_id=
  GET  /retained?tenant_id=
  GET  /metrics

Headers (tenant_id etc.) are also accepted in the reference style
(`x-tenant-id`, `x-client-id`...); query params win.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Dict, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

from ..mqtt.broker import MQTTBroker
from ..types import ClientInfo, Message, QoS
from ..utils import topic as topic_util
from ..utils.env import env_float as _env_float
from ..utils.hlc import HLC

log = logging.getLogger("bifromq_tpu.api")


class APIServer:
    def __init__(self, broker: MQTTBroker, host: str = "127.0.0.1",
                 port: int = 0, *, cluster=None, metrics=None,
                 registry=None, clusterview=None) -> None:
        self.broker = broker
        self.host = host
        self.port = port
        self.cluster = cluster
        self.metrics = metrics
        self.registry = registry    # rpc.fabric.ServiceRegistry (clustered)
        self.clusterview = clusterview  # obs.clusterview.ClusterView
        self._server: Optional[asyncio.AbstractServer] = None
        # ISSUE 8 satellite: periodic merged /cluster/tenants cache —
        # (monotonic stamp, full merged payload); served with max-age /
        # age headers instead of scatter-gathering per request
        self._tenants_cache: Optional[Tuple[float, dict]] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._on_client, self.host,
                                                  self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # ---------------- http plumbing ----------------------------------------

    async def _on_client(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                request = await self._read_request(reader)
                if request is None:
                    break
                method, path, headers, body = request
                result = await self._route(method, path, headers, body)
                # handlers return (status, payload) or, when they carry
                # response headers (ISSUE 8: the tenants cache's max-age
                # / age pair), (status, payload, extra_headers)
                if len(result) == 3:
                    status, payload, extra = result
                else:
                    status, payload = result
                    extra = {}
                data = json.dumps(payload).encode() + b"\n"
                reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                          429: "Too Many Requests",
                          500: "Internal Server Error"}.get(status, "Status")
                head = (f"HTTP/1.1 {status} {reason}\r\n"
                        f"content-type: application/json\r\n")
                for k, v in extra.items():
                    head += f"{k}: {v}\r\n"
                writer.write(
                    (head + f"content-length: {len(data)}\r\n\r\n").encode()
                    + data)
                await writer.drain()
                if headers.get("connection", "").lower() == "close":
                    break
        except (ConnectionError, asyncio.IncompleteReadError, ValueError):
            pass
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _read_request(self, reader: asyncio.StreamReader):
        line = await reader.readline()
        if not line:
            return None
        try:
            method, path, _ = line.decode().split(" ", 2)
        except ValueError:
            return None
        headers: Dict[str, str] = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            k, _, v = h.decode().partition(":")
            headers[k.strip().lower()] = v.strip()
        body = b""
        n = int(headers.get("content-length", "0") or 0)
        if n:
            body = await reader.readexactly(n)
        return method.upper(), path, headers, body

    # ---------------- routing ----------------------------------------------

    async def _route(self, method: str, path: str, headers: Dict[str, str],
                     body: bytes) -> Tuple[int, object]:
        url = urlsplit(path)
        q = {k: v[0] for k, v in parse_qs(url.query).items()}

        def arg(name: str, default: Optional[str] = None) -> Optional[str]:
            return q.get(name, headers.get(f"x-{name.replace('_', '-')}",
                                           default))

        route = (method, url.path)
        try:
            if route == ("PUT", "/pub"):
                return await self._pub(arg, body)
            if route == ("PUT", "/sub"):
                return await self._sub(arg)
            if route == ("DELETE", "/unsub"):
                return await self._unsub(arg)
            if route == ("DELETE", "/kill"):
                return await self._kill(arg)
            if route == ("DELETE", "/session"):
                return await self._expire_session(arg)
            if route == ("PUT", "/retain"):
                return await self._retain(arg, body)
            if route == ("GET", "/cluster"):
                return self._cluster_info()
            if route == ("GET", "/cluster/tenants"):
                return await self._cluster_tenants(arg)
            if route == ("GET", "/cluster/capacity"):
                return self._cluster_capacity()
            if route == ("GET", "/cluster/slo"):
                return self._cluster_slo()
            if route == ("GET", "/capacity"):
                return self._capacity_get(arg)
            if route == ("GET", "/replication"):
                # ISSUE 12: delta-stream status — per-range heads on the
                # hosting worker, standby cursors/lag, puller cursors
                from .. import replication
                return 200, replication.status_report()
            if route == ("GET", "/profile"):
                return self._profile_get(arg)
            if method == "GET" and url.path.startswith("/cluster/trace/"):
                return await self._cluster_trace(
                    url.path[len("/cluster/trace/"):], arg)
            if route == ("GET", "/cluster/route"):
                return self._cluster_route(arg)
            if route == ("GET", "/sessions"):
                return self._sessions(arg)
            if route == ("GET", "/inbox-state"):
                return await self._inbox_state(arg)
            if route == ("GET", "/routes"):
                return self._routes(arg)
            if route == ("GET", "/retained"):
                return self._retained(arg)
            if route == ("GET", "/mesh"):
                return self._mesh_get()
            if route == ("GET", "/mesh/rebalance"):
                return self._mesh_rebalance(arg)
            if route == ("GET", "/mesh/migrations"):
                return self._mesh_migrations()
            if route == ("GET", "/mesh/autoscaler"):
                return self._mesh_autoscaler(arg)
            if route == ("GET", "/replication/lag"):
                return self._replication_lag(arg)
            if route == ("GET", "/slo"):
                return self._slo_get(arg)
            if route == ("GET", "/metrics"):
                return self._metrics_get(arg)
            if route == ("GET", "/tenants"):
                return self._tenants_ranked(arg)
            if method == "GET" and url.path.startswith("/tenants/"):
                from urllib.parse import unquote
                return self._tenant_detail(
                    unquote(url.path[len("/tenants/"):]))
            if route == ("GET", "/obs"):
                return self._obs_state()
            if route == ("PUT", "/obs"):
                return self._obs_config(arg)
            if route == ("GET", "/trace"):
                return self._trace_get(arg, slow=False)
            if route == ("GET", "/trace/slow"):
                return self._trace_get(arg, slow=True)
            if route == ("PUT", "/trace"):
                return self._trace_config(arg)
            if route == ("GET", "/ranges"):
                return self._ranges()
            if route == ("GET", "/balancer"):
                return self._balancer_state()
            if route == ("PUT", "/balancer"):
                return self._balancer_toggle(arg)
            if route == ("PUT", "/balancer-rules"):
                return self._balancer_rules_set(arg, body)
            if route == ("GET", "/traffic"):
                return self._traffic_get()
            if route == ("PUT", "/traffic"):
                return self._traffic_set(arg, body)
            if route == ("DELETE", "/traffic"):
                return self._traffic_unset(arg)
            return 404, {"error": f"no route {method} {url.path}"}
        except KeyError as e:
            return 400, {"error": f"missing parameter {e}"}
        except ValueError as e:
            return 400, {"error": f"bad parameter: {e}"}
        except Exception as e:  # noqa: BLE001 — a handler bug must surface
            log.exception("api handler failed: %s %s", method, url.path)
            return 500, {"error": repr(e)}

    # ---------------- handlers ---------------------------------------------

    async def _pub(self, arg, body: bytes) -> Tuple[int, object]:
        tenant = arg("tenant_id") or "DevOnly"
        topic = arg("topic")
        if not topic or not topic_util.is_valid_topic(topic):
            return 400, {"error": "invalid topic"}
        qos = int(arg("qos", "0"))
        msg = Message(message_id=0, pub_qos=QoS(qos), payload=body,
                      timestamp=HLC.INST.get(),
                      is_retain=arg("retain", "false") == "true")
        publisher = ClientInfo(tenant_id=tenant, type="API")
        if msg.is_retain and self.broker.retain_service is not None:
            await self.broker.retain_service.retain(publisher, topic, msg)
        result = await self.broker.dist.pub(publisher, topic, msg)
        return 200, {"fanout": result.fanout}

    async def _sub(self, arg) -> Tuple[int, object]:
        """Sub-on-behalf (≈ SessionDictService.sub): a LIVE session gets
        the subscription through its own session object (permission checks,
        retained delivery, route registration all apply); only an OFFLINE
        persistent session falls back to the direct inbox write."""
        tenant = arg("tenant_id") or "DevOnly"
        client_id = arg("client_id")
        tf = arg("topic_filter")
        if not client_id or not tf:
            return 400, {"error": "client_id and topic_filter required"}
        if not topic_util.is_valid_topic_filter(tf):
            return 400, {"error": "invalid topic filter"}
        qos = int(arg("qos", "0"))
        res = await self._live_on_behalf("sub", tenant, client_id, tf, qos)
        if res is not None and res != "no_session":
            code = 200 if res in ("ok", "exists") else 403
            return code, {"result": res, "live": True}
        from ..types import TopicFilterOption
        res = await self.broker.inbox.sub(tenant, client_id, tf,
                                    TopicFilterOption(qos=QoS(qos)))
        if res == "no_inbox":
            return 404, {"error": "no such session (live or persistent)"}
        return 200, {"result": res}

    async def _unsub(self, arg) -> Tuple[int, object]:
        tenant = arg("tenant_id") or "DevOnly"
        client_id = arg("client_id")
        tf = arg("topic_filter")
        if not client_id or not tf:
            return 400, {"error": "client_id and topic_filter required"}
        res = await self._live_on_behalf("unsub", tenant, client_id, tf)
        if res is not None and res != "no_session":
            code = 200 if res == "ok" else (404 if res == "no_sub" else 403)
            return code, {"result": res, "live": True}
        removed = await self.broker.inbox.unsub(tenant, client_id, tf)
        return (200 if removed else 404), {"removed": removed}

    async def _live_on_behalf(self, op: str, tenant: str, client_id: str,
                              tf: str, qos: int = 0):
        """Try the live session: local registry first, then the cluster
        session dict. Returns a result name or None/no_session."""
        session = self.broker.session_registry.get(tenant, client_id)
        if session is not None and not session.closed:
            if op == "sub":
                return await session.admin_sub(tf, qos)
            return await session.admin_unsub(tf)
        sd = getattr(self.broker, "session_dict", None)
        if sd is not None:
            try:
                if op == "sub":
                    return await sd.sub(tenant, client_id, tf, qos)
                return await sd.unsub(tenant, client_id, tf)
            except Exception:  # noqa: BLE001 — dict unavailable: fall back
                return None
        return None

    async def _inbox_state(self, arg) -> Tuple[int, object]:
        """Live-session state (≈ SessionDictService.inboxState)."""
        tenant = arg("tenant_id") or "DevOnly"
        client_id = arg("client_id")
        if not client_id:
            return 400, {"error": "client_id required"}
        session = self.broker.session_registry.get(tenant, client_id)
        if session is not None and not session.closed:
            return 200, session.inbox_state()
        sd = getattr(self.broker, "session_dict", None)
        if sd is not None:
            try:
                state = await sd.inbox_state(tenant, client_id)
            except Exception:  # noqa: BLE001
                state = None
            if state is not None:
                return 200, state
        return 404, {"error": "no live session"}

    async def _kill(self, arg) -> Tuple[int, object]:
        tenant = arg("tenant_id") or "DevOnly"
        client_id = arg("client_id")
        session = self.broker.session_registry.get(tenant, client_id or "")
        if session is None:
            return 404, {"error": "not connected"}
        await session.kick()
        return 200, {"killed": client_id}

    async def _expire_session(self, arg) -> Tuple[int, object]:
        tenant = arg("tenant_id") or "DevOnly"
        client_id = arg("client_id")
        existed = self.broker.inbox.store.exists(tenant, client_id or "")
        await self.broker.inbox.delete(tenant, client_id or "")
        return (200 if existed else 404), {"deleted": existed}

    async def _retain(self, arg, body: bytes) -> Tuple[int, object]:
        tenant = arg("tenant_id") or "DevOnly"
        topic = arg("topic")
        if not topic or not topic_util.is_valid_topic(topic):
            return 400, {"error": "invalid topic"}
        msg = Message(message_id=0, pub_qos=QoS.AT_MOST_ONCE, payload=body,
                      timestamp=HLC.INST.get(), is_retain=True)
        ok = await self.broker.retain_service.retain(
            ClientInfo(tenant_id=tenant, type="API"), topic, msg)
        return (200 if ok else 429), {"retained": ok and bool(body)}

    # -- flight recorder (ISSUE 2: /trace, /trace/slow + sampling knobs) ----

    def _trace_get(self, arg, slow: bool) -> Tuple[int, object]:
        from .. import trace as tr
        spans = tr.TRACER.export(trace_id=arg("trace_id"),
                                 tenant=arg("tenant_id"),
                                 limit=int(arg("limit", "256")),
                                 slow=slow)
        return 200, {"count": len(spans),
                     "dropped": (tr.TRACER.slow_ring if slow
                                 else tr.TRACER.ring).dropped,
                     "sampling": tr.TRACER.sampler.snapshot(),
                     "slow_ms": tr.TRACER.slow_ms,
                     "spans": spans}

    def _trace_config(self, arg) -> Tuple[int, object]:
        """Runtime sampling knobs: ``rate`` (0..1, per-tenant when
        ``tenant_id`` is given, else the process default) and ``slow_ms``
        (0 disarms the always-on-slow capture)."""
        from .. import trace as tr
        # parse EVERYTHING before applying anything: a 400 on a bad knob
        # must not leave sampling half-reconfigured
        rate = arg("rate")
        r = float(rate) if rate is not None else None
        slow = arg("slow_ms")
        v = float(slow) if slow is not None else None
        if r is not None:
            tenant = arg("tenant_id")
            if tenant:
                tr.TRACER.sampler.set_rate(tenant, r)
            else:
                tr.TRACER.sampler.default_rate = r
        if v is not None:
            tr.TRACER.slow_ms = v if v > 0 else None
        return 200, {"sampling": tr.TRACER.sampler.snapshot(),
                     "slow_ms": tr.TRACER.slow_ms}

    # -- tenant SLO surface (ISSUE 3: /tenants, /tenants/<id>, /obs) --------

    def _metrics_get(self, arg) -> Tuple[int, object]:
        """/metrics: the registry snapshot composed with the obs-layer
        sections (composition lives HERE so utils.metrics stays below the
        obs hub). ``?tenant=<id>`` is the lean per-tenant scrape — that
        tenant's counters + SLO window, no fabric/stages/device payload."""
        from ..obs import OBS
        if self.metrics is None:
            return 200, {}
        tenant = arg("tenant")
        snap = self.metrics.snapshot(tenant=tenant)
        if tenant is not None:
            snap["slo"] = ({tenant: OBS.windows.snapshot_tenant(tenant)}
                           if OBS.enabled else {})
        else:
            snap["device"] = OBS.device_snapshot()
            snap["obs"] = OBS.obs_snapshot()
            # ISSUE 13: retained scan planes + drain governors (absent
            # key when neither exists — lean default scrape)
            retained = OBS.retained_snapshot()
            if retained["scan_planes"] or retained["drain_governors"]:
                snap["retained"] = retained
            # ISSUE 17: mesh shard-load rows + in-flight migrations
            # (absent key on single-chip nodes — lean default scrape)
            mesh = OBS.mesh_snapshot()
            if mesh:
                snap["mesh"] = {"shard_load": mesh}
            # ISSUE 10: graftcheck build-info (rule count, suppression
            # count, last-run hash) — two live nodes disagreeing on the
            # hash are running different code or different suppressions
            from ..analysis import build_info
            snap["build_info"] = {"graftcheck": build_info()}
        return 200, snap

    def _mesh_get(self) -> Tuple[int, object]:
        """/mesh: every live mesh matcher's shard map — per-shard load
        rows (bytes / logical subs / heat / queue pressure / breaker),
        skew, map version, in-flight migrations, pins and replicas
        (ISSUE 17). 404 on a single-chip node: there is no shard map."""
        from ..obs import OBS
        meshes = OBS.mesh_snapshot()
        if not meshes:
            return 404, {"error": "no mesh matcher on this node"}
        return 200, {"meshes": meshes}

    def _mesh_rebalance(self, arg) -> Tuple[int, object]:
        """/mesh/rebalance: the rebalancer's decision log — executed
        moves (tenant/src/dst, skew before/after, capacity vetoes) and
        the live skew it would act on next. Read-only: driving a
        migration is a control-plane call, not a scrape side effect."""
        from ..obs import OBS
        top_k = int(arg("top_k", "10"))
        if top_k < 0:
            return 400, {"error": f"top_k={top_k} (must be >= 0)"}
        out = []
        for m in OBS.device.matchers():
            status = getattr(m, "mesh_status", None)
            if status is None:
                continue
            try:
                s = status()
            except Exception:  # noqa: BLE001 — telemetry must not raise
                continue
            reb = getattr(m, "mesh_rebalancer", None)
            out.append({
                "skew": s.get("skew"),
                "map_version": s.get("map_version"),
                "migrating": s.get("migrating", {}),
                "decisions": (list(reb.decisions)[-top_k:]
                              if reb is not None else []),
            })
        if not out:
            return 404, {"error": "no mesh matcher on this node"}
        return 200, {"rebalancers": out}

    def _mesh_migrations(self) -> Tuple[int, object]:
        """/mesh/migrations: the live-migration ladder, rung by rung —
        per in-flight migration the copy-stream progress (chunks, rows,
        bytes, %), the dual-serve-window duration and the current rung;
        per retired migration the per-rung timings and the abort
        attribution (ISSUE 18). 404 on a single-chip node."""
        from ..obs import OBS
        from ..parallel.reshard import migration_digest
        out = []
        for m in OBS.device.matchers():
            if getattr(m, "mesh_status", None) is None:
                continue
            active = [mig.progress() for mig in
                      getattr(m, "migrations_inflight", {}).values()]
            out.append({
                "digest": migration_digest(m),
                "active": active,
                "history": list(getattr(m, "migration_history", [])),
            })
        if not out:
            return 404, {"error": "no mesh matcher on this node"}
        return 200, {"migrations": out}

    def _mesh_autoscaler(self, arg) -> Tuple[int, object]:
        """/mesh/autoscaler: the unattended scaling loop's knobs and its
        bounded decision ring — every grow/rebalance/shrink/veto with
        the exact signal snapshot it acted on (ISSUE 18 provenance:
        'why did the mesh grow at 3am' is answerable from one GET)."""
        from ..obs import OBS
        top_k = int(arg("top_k", "32"))
        if top_k < 0:
            return 400, {"error": f"top_k={top_k} (must be >= 0)"}
        out = []
        for m in OBS.device.matchers():
            scaler = getattr(m, "mesh_autoscaler", None)
            if scaler is None:
                continue
            st = scaler.status()
            st["decisions"] = st["decisions"][-top_k:]
            out.append(st)
        if not out:
            return 404, {"error": "no autoscaler on this node"}
        return 200, {"autoscalers": out}

    def _replication_lag(self, arg) -> Tuple[int, object]:
        """/replication/lag: the ISSUE 18 lag plane — per (origin,
        range) stream the windowed apply-lag histogram, throughput,
        reorder occupancy, resync/gap counters and the stale flag, plus
        the recent delta-plane event journal."""
        from ..obs.lag import LAG, REPL_EVENTS
        top_k = int(arg("events", "64"))
        if top_k < 0:
            return 400, {"error": f"events={top_k} (must be >= 0)"}
        snap = LAG.snapshot()
        snap["events"] = REPL_EVENTS.tail(top_k)
        return 200, snap

    def _slo_get(self, arg) -> Tuple[int, object]:
        """``GET /slo``: the ISSUE 20 delivery-SLO plane — per-tenant
        multi-window burn-rate state (objectives, fast/slow burns, the
        burning set), the full-population publish→deliver latency
        histograms per (tenant, qos, path) with violation counters and
        degraded attribution, plus the recent SLO_BURN / SLO_RECOVERED
        journal (``?events=`` caps the tail)."""
        from ..obs import OBS
        from ..obs.burnrate import SLO_EVENTS
        top_k = int(arg("events", "64"))
        if top_k < 0:
            return 400, {"error": f"events={top_k} (must be >= 0)"}
        return 200, {"burn": OBS.burnrate.snapshot(),
                     "e2e": OBS.e2e.snapshot(),
                     "events": SLO_EVENTS.tail(top_k)}

    def _tenants_ranked(self, arg) -> Tuple[int, object]:
        """Live noisy-neighbor ranking over the windowed RED state: top-K
        tenants by blended contention score, flags included. Evaluation
        also refreshes the throttler advisory and emits NOISY_TENANT /
        SLOW_TENANT events (cooldown-limited)."""
        from ..obs import OBS
        top_k = int(arg("top_k", "10"))
        if top_k < 0:
            return 400, {"error": f"top_k={top_k} (must be >= 0)"}
        return 200, OBS.tenants_snapshot(top_k=top_k)

    def _tenant_detail(self, tenant: str) -> Tuple[int, object]:
        """One tenant's full SLO state: windowed RED + per-stage windows,
        the ranked row (score/shares/flags), and the monotonic counters."""
        from ..obs import OBS
        if not tenant:
            return 400, {"error": "tenant id required"}
        windows = OBS.windows.snapshot_tenant(tenant)
        row = OBS.detector.score_tenant(tenant) if OBS.enabled else None
        counters = {}
        if self.metrics is not None:
            counters = self.metrics.tenant_counters(tenant)
        # ISSUE 20: burn-rate state + e2e delivery latency ride the view
        burn = OBS.burnrate.snapshot_tenant(tenant)
        e2e = OBS.e2e.snapshot_tenant(tenant)
        if not windows and not counters and not burn and not e2e:
            return 404, {"error": f"no SLO state for tenant {tenant!r}"}
        return 200, {"tenant": tenant,
                     "window_s": OBS.windows.window_s,
                     "slo": windows,
                     "rank": row,
                     "counters": counters,
                     "burn": burn,
                     "e2e": e2e}

    def _obs_state(self) -> Tuple[int, object]:
        from ..obs import OBS
        return 200, {**OBS.obs_snapshot(),
                     "window_s": OBS.windows.window_s,
                     "noisy_threshold": OBS.detector.noisy_threshold,
                     "slow_p99_ms": OBS.detector.slow_p99_ms,
                     "detector": OBS.detector.config_snapshot(),
                     # ISSUE 20: the burn engine's live config rides the
                     # same state view PUT /obs returns
                     "slo": OBS.burnrate.snapshot()}

    def _obs_config(self, arg) -> Tuple[int, object]:
        """Runtime SLO knobs: ``windows`` (0/1 toggles the window layer),
        ``noisy_threshold``, ``slow_p99_ms``, blend weights (``w_fanout``
        / ``w_queue_wait`` / ``w_errors``). With ``tenant_id`` set the
        threshold/weight knobs install a per-tenant override instead
        (ISSUE 5 satellite; ``clear=1`` drops that tenant's overrides).
        ISSUE 20 adds the burn-rate engine's knobs: process-wide
        ``slo_fast_window_s`` / ``slo_slow_window_s`` /
        ``slo_burn_threshold`` / ``slo_cooldown_s`` / ``slo_p99_ms`` /
        ``slo_success``; with ``tenant_id`` set, ``slo_p99_ms`` /
        ``slo_success`` install a per-tenant objective instead.
        Parse everything before applying anything (same contract as
        PUT /trace)."""
        from ..obs import OBS
        det = OBS.detector
        raw_windows = arg("windows")
        windows = None
        if raw_windows is not None:
            low = raw_windows.lower()
            if low in ("1", "true", "on"):
                windows = True
            elif low in ("0", "false", "off"):
                windows = False
            else:
                return 400, {"error": f"windows={raw_windows!r}"}
        knobs = {}
        for name in sorted(det.TENANT_KNOBS):
            raw = arg(name)
            if raw is not None:
                knobs[name] = float(raw)      # ValueError → 400 upstream
        slo = {}
        for qname, kname in (("slo_fast_window_s", "fast_window_s"),
                             ("slo_slow_window_s", "slow_window_s"),
                             ("slo_burn_threshold", "burn_threshold"),
                             ("slo_cooldown_s", "cooldown_s"),
                             ("slo_p99_ms", "p99_ms"),
                             ("slo_success", "success")):
            raw = arg(qname)
            if raw is not None:
                slo[kname] = float(raw)       # ValueError → 400 upstream
        tenant = arg("tenant_id")
        if tenant and any(k not in ("p99_ms", "success") for k in slo):
            return 400, {"error": "per-tenant SLO overrides accept only "
                                  "slo_p99_ms / slo_success"}
        if windows is not None:       # process-wide regardless of tenant
            OBS.enabled = windows
        if tenant:
            # clear-then-set: ?clear=1&slow_p99_ms=150 drops the old
            # override and installs the new knob, never discards it
            if arg("clear") in ("1", "true"):
                det.clear_tenant(tenant)
                OBS.burnrate.clear_tenant(tenant)
            if knobs:
                det.configure_tenant(tenant, **knobs)
            if slo:
                OBS.burnrate.configure_tenant(tenant, **slo)
        else:
            # process-wide defaults: noisy_threshold / slow_p99_ms / w_*
            for name, v in knobs.items():
                setattr(det, name, v)
            if slo:
                OBS.burnrate.configure(**slo)
        return self._obs_state()

    def _cluster_info(self) -> Tuple[int, object]:
        """``GET /cluster``: the merged node table (ISSUE 5) — liveness,
        gossiped health digest + its age, and hosted agents per member.
        Falls back to the plain membership table when no cluster view is
        wired (and to standalone when there is no cluster at all)."""
        if self.cluster is None:
            return 200, {"mode": "standalone"}
        if self.clusterview is not None:
            return 200, {"mode": "cluster",
                         "self": self.clusterview.node_id,
                         "unhealthy_endpoints":
                             self.clusterview.unhealthy_endpoints(),
                         "members": self.clusterview.cluster_table()}
        return 200, {
            "mode": "cluster",
            "members": {m.node_id: {"status": m.status,
                                    "agents": sorted(m.agents)}
                        for m in self.cluster.members.values()},
        }

    async def _cluster_tenants(self, arg) -> Tuple:
        """``GET /cluster/tenants``: per-tenant RED merged across every
        node (scatter-gather under a deadline budget; log2 histograms
        merged bucket-wise). Standalone/unwired nodes degrade to a
        local-only view with the same shape.

        ISSUE 8 satellite: the merged view is CACHED — a scrape loop or
        dashboard polling every second no longer fans an RPC out to
        every node per request. The full (top_k=0) merge is cached for
        ``BIFROMQ_CLUSTER_TENANTS_TTL_S`` (request override:
        ``?max_age_s=``, 0 forces a refresh); top_k filtering applies
        per request on the cached rows, and the response carries
        ``cache-control: max-age`` + ``age`` headers so consumers can
        see exactly how fresh the merge is."""
        top_k = int(arg("top_k", "0"))
        timeout_s = float(arg("timeout_s", "2.0"))
        ttl = float(arg("max_age_s", "") or _env_float(
            "BIFROMQ_CLUSTER_TENANTS_TTL_S", 2.0))
        now = time.monotonic()
        cached = self._tenants_cache
        if cached is not None and ttl > 0 and now - cached[0] < ttl:
            age = now - cached[0]
            out = cached[1]
        else:
            out = await self._cluster_tenants_fetch(timeout_s)
            self._tenants_cache = (now, out)
            age = 0.0
        payload = dict(out)
        rows = payload.get("tenants") or {}
        if top_k > 0:       # filter per request; the cache stays full
            keep = sorted(rows,
                          key=lambda t: -rows[t]["rate_per_s"])[:top_k]
            payload["tenants"] = {t: rows[t] for t in keep}
        payload["cache"] = {"age_s": round(age, 3), "max_age_s": ttl}
        return 200, payload, {"cache-control": f"max-age={ttl:g}",
                              "age": f"{age:.3f}"}

    async def _cluster_tenants_fetch(self, timeout_s: float) -> dict:
        """One full (unfiltered) merge — the cache's fill path."""
        if self.clusterview is not None:
            return await self.clusterview.federated_tenants(
                timeout_s=timeout_s, top_k=0)
        from ..obs import OBS
        from ..obs.clusterview import derive_red_row, merge_tenant_raws
        merged = merge_tenant_raws(
            [OBS.windows.raw_snapshot() if OBS.enabled else {}])
        rows = {t: derive_red_row(r, OBS.windows.window_s)
                for t, r in merged.items()}
        return {"window_s": OBS.windows.window_s,
                "nodes": {OBS.node_id: "local"},
                "tenants": rows}

    # -- capacity & profiling plane (ISSUE 8) -------------------------------

    def _capacity_get(self, arg) -> Tuple[int, object]:
        """``GET /capacity``: model-vs-live byte parity for every
        registered matcher, guarded HBM stats, planner coefficients;
        ``?n_subs=`` (+ optional ``shards=``) adds a full ``fits``
        verdict — HBM headroom and the fused-VMEM gate — computed
        without dispatching anything. ``?calibrate=1`` (ISSUE 11
        satellite, ROADMAP sharding follow-up (c)) re-fits the per-sub
        coefficients from the live base with its true logical sub count
        and reports old-vs-new deltas; the ``fits`` verdict then uses
        the re-fit planner."""
        from ..obs.capacity import capacity_report
        kw = {}
        n_subs = arg("n_subs")
        if n_subs is not None:
            kw["n_subs"] = int(n_subs)
        shards = arg("shards")
        if shards is not None:
            kw["mesh"] = int(shards)
        if arg("calibrate", "0") in ("1", "true"):
            kw["calibrate"] = True
        return 200, capacity_report(
            memory=arg("memory", "1") != "0", **kw)

    def _profile_get(self, arg) -> Tuple[int, object]:
        """``GET /profile``: the continuous profiler's live snapshot —
        dispatch/ready/fetch split with the tunnel-RTT vs kernel-time
        decomposition, padding waste, dedup savings, cache bypasses,
        the compile-event ledger, and segment-store state. The RTT
        shown is the cached estimate; ``?probe=1`` pays a fresh device
        round-trip probe (blocks this handler ~4×RTT — explicit
        operator opt-in, never the scrape-loop default)."""
        from ..obs import OBS
        return 200, OBS.profile_snapshot(
            brief=arg("brief", "0") in ("1", "true"),
            probe=arg("probe", "0") in ("1", "true"))

    def _cluster_capacity(self) -> Tuple[int, object]:
        """``GET /cluster/capacity``: per-node capacity federated from
        the gossiped health digests (no scatter-gather RPC)."""
        if self.clusterview is not None:
            return 200, self.clusterview.capacity_table()
        from ..obs import OBS
        from ..obs.capacity import digest_capacity
        local = digest_capacity(OBS)
        ls = int(local.get("logical_subs", 0))
        return 200, {"nodes": {OBS.node_id: {"capacity": local,
                                             "stale": False,
                                             "self": True}},
                     "total_table_bytes": local.get("table_bytes", 0),
                     "max_mem_peak_bytes": local.get("mem_peak_bytes", 0),
                     "logical_subs": {"sum": ls, "dedup": ls,
                                      "replica_groups": 1 if ls else 0}}

    def _cluster_slo(self) -> Tuple[int, object]:
        """``GET /cluster/slo``: per-node burn summaries federated from
        the gossiped health digests (no scatter-gather RPC) — which
        tenants are burning anywhere in the cluster, and the worst
        burner per node."""
        from ..obs import OBS
        local = OBS.burnrate.summary()
        nodes = {OBS.node_id: {"slo": local, "stale": False,
                               "self": True}}
        if self.clusterview is not None:
            for node, p in self.clusterview.peers().items():
                nodes[node] = {"slo": (p["digest"] or {}).get("slo", {}),
                               "stale": p["stale"]}
        burning = sorted({t for n in nodes.values()
                          for t in (n["slo"] or {}).get("burning", [])})
        return 200, {"nodes": nodes, "burning": burning}

    async def _cluster_trace(self, trace_id: str, arg) -> Tuple[int, object]:
        """``GET /cluster/trace/<id>``: the full cross-process trace,
        every peer's span rings queried and the union ordered by HLC."""
        if not trace_id:
            return 400, {"error": "trace id required"}
        timeout_s = float(arg("timeout_s", "2.0"))
        if self.clusterview is not None:
            return 200, await self.clusterview.federated_trace(
                trace_id, timeout_s=timeout_s)
        from .. import trace as tr
        from ..obs import OBS
        spans = tr.TRACER.export(trace_id=trace_id, limit=1000)
        return 200, {"trace_id": trace_id, "count": len(spans),
                     "nodes": {OBS.node_id: "local"},
                     "processes": 1 if spans else 0,
                     "spans": [dict(s, node=OBS.node_id) for s in spans]}

    def _cluster_route(self, arg) -> Tuple[int, object]:
        """``GET /cluster/route?service=&key=``: where would this tenant
        key route right now? Operator introspection for the health-aware
        rendezvous pick (and the tier-2 cluster gate's probe)."""
        if self.registry is None:
            return 404, {"error": "no service registry (standalone mode)"}
        service = arg("service")
        if not service:
            return 400, {"error": "missing parameter 'service'"}
        key = arg("key") or ""
        rh = self.registry.remote_health
        return 200, {
            "service": service,
            "key": key,
            "endpoint": self.registry.pick(service, key),
            "endpoints": self.registry.endpoints(service),
            "unhealthy": (rh.unhealthy_endpoints()
                          if rh is not None
                          and hasattr(rh, "unhealthy_endpoints") else []),
        }

    def _sessions(self, arg) -> Tuple[int, object]:
        tenant = arg("tenant_id") or "DevOnly"
        online = self.broker.session_registry.client_ids(tenant)
        persistent = [i for t, i, m in self.broker.inbox.store.all_inboxes()
                      if t == tenant]
        return 200, {"online": sorted(online),
                     "persistent": sorted(persistent)}

    def _ranges(self) -> Tuple[int, object]:
        """Per-range observability (≈ KVRangeMetricManager): key counts,
        raft health, and the load profile feeding the split hinters —
        for the dist, inbox, and retain stores."""
        from ..kv.metrics import range_stats

        out = {}
        worker_store = getattr(self.broker.dist.worker, "store", None)
        if worker_store is not None:
            out["dist"] = range_stats(worker_store)
        inbox_store = getattr(self.broker.inbox, "kvstore", None)
        if inbox_store is not None:
            out["inbox"] = range_stats(inbox_store)
        retain_store = getattr(self.broker.retain_service, "kvstore", None)
        if retain_store is not None:
            out["retain"] = range_stats(retain_store)
        return 200, out

    # -- balancer admin (≈ apiserver balancer enable/disable/state handlers)

    def _controllers(self) -> Dict[str, object]:
        out: Dict[str, object] = {}
        ctl = getattr(getattr(self.broker.dist, "worker", None),
                      "balance_controller", None)
        if ctl is not None:
            out["dist"] = ctl
        for name, svc in (("inbox", self.broker.inbox),
                          ("retain", self.broker.retain_service)):
            c = getattr(svc, "balance_controller", None)
            if c is not None:
                out[name] = c
        return out

    def _balancer_state(self) -> Tuple[int, object]:
        return 200, {name: c.state()
                     for name, c in self._controllers().items()}

    def _balancer_rules_set(self, arg, body: bytes) -> Tuple[int, object]:
        """Install declarative placement rules on a store's controller
        (≈ KVStoreBalanceController.updateLoadRules via the reference's
        PUT LoadRules admin API). Body: the rule JSON document."""
        try:
            rules = json.loads(body.decode() or "{}")
        except ValueError:
            return 400, {"error": "body must be a JSON rule document"}
        target = arg("store")      # omit = all rule-capable controllers
        hit = []
        for name, c in self._controllers().items():
            if target in (None, name):
                if not hasattr(c, "set_rules"):
                    if target == name:
                        return 400, {"error":
                                     f"controller {name!r} takes no rules"}
                    continue
                err = c.set_rules(rules)
                if err is not None:
                    return 400, {"error": err}
                hit.append(name)
        if not hit:
            return 404, {"error": f"no rule-capable controller {target!r}"}
        return 200, {"rules": rules, "stores": hit}

    def _balancer_toggle(self, arg) -> Tuple[int, object]:
        raw = (arg("enable") or "true").lower()
        if raw in ("1", "true", "yes", "on"):
            enable = True
        elif raw in ("0", "false", "no", "off"):
            enable = False
        else:
            # a typo must not silently disable elasticity cluster-wide
            return 400, {"error": f"enable={raw!r} (use true|false)"}
        target = arg("store")      # omit = all
        hit = []
        for name, c in self._controllers().items():
            if target in (None, name):
                c.enabled = enable
                hit.append(name)
        if not hit:
            return 404, {"error": f"no balance controller {target!r}"}
        return 200, {"enabled": enable, "stores": hit}

    # -- traffic directives (≈ apiserver traffic-rules handlers over the
    #    RPC traffic governor)

    def _traffic_get(self) -> Tuple[int, object]:
        if self.registry is None:
            return 404, {"error": "no service registry (standalone mode)"}
        return 200, self.registry.traffic_directives()

    def _traffic_set(self, arg, body: bytes) -> Tuple[int, object]:
        if self.registry is None:
            return 404, {"error": "no service registry (standalone mode)"}
        service = arg("service")
        if not service:
            return 400, {"error": "missing parameter 'service'"}
        groups = json.loads(body or b"{}")
        # a bad weight stored here would TypeError inside every routed RPC
        # for matching tenants — reject at the admin boundary instead
        if (not isinstance(groups, dict)
                or not all(isinstance(w, int) and not isinstance(w, bool)
                           and w >= 0 for w in groups.values())):
            return 400, {"error": "body must be {server_group: weight>=0}"}
        self.registry.set_traffic_directive(
            service, arg("tenant_prefix") or "", groups)
        return 200, {"ok": True}

    def _traffic_unset(self, arg) -> Tuple[int, object]:
        if self.registry is None:
            return 404, {"error": "no service registry (standalone mode)"}
        service = arg("service")
        if not service:
            return 400, {"error": "missing parameter 'service'"}
        self.registry.unset_traffic_directive(
            service, arg("tenant_prefix") or "")
        return 200, {"ok": True}

    def _routes(self, arg) -> Tuple[int, object]:
        tenant = arg("tenant_id") or "DevOnly"
        trie = self.broker.dist.matcher.tries.get(tenant)
        routes = []
        if trie is not None:
            for r in trie.routes():
                routes.append({"filter": r.matcher.mqtt_topic_filter,
                               "broker": r.broker_id,
                               "receiver": r.receiver_id})
        return 200, {"count": len(routes), "routes": routes[:1000]}

    def _retained(self, arg) -> Tuple[int, object]:
        tenant = arg("tenant_id") or "DevOnly"
        svc = self.broker.retain_service
        topics = svc.topics(tenant) if svc else []
        return 200, {"count": len(topics), "topics": topics[:1000]}
