"""bifromq_tpu.apiserver — HTTP management API (analog of bifromq-apiserver)."""
from .server import APIServer

__all__ = ["APIServer"]
