"""Filter-keyed retained scan cache + the retained delta stream
(ISSUE 13 tentpole part 2, cache half).

``RetainedScanCache`` memoizes wildcard-scan results per (tenant,
filter, limit). Retained mutations are CONCRETE topics, so exact
invalidation is a containment test, not a guess: a SET/DEL of topic T
evicts precisely the cached filters that match T
(``utils.topic.matches`` — the same [MQTT-4.7.2-1]-aware predicate the
oracle uses). A tenant whose key population outgrows the scan bound
degrades to one per-tenant epoch bump (the wholesale semantics a TTL
would have provided, minus the wait). Pre-scan tokens defeat stores
racing in-flight scans — the same discipline as the route-match cache.

``RetainedDeltaLog`` is the seq'd per-range stream of those mutations,
riding the PR 12 replication surfaces: it registers with the
replication status registry (``GET /replication`` shows retained heads
next to the route hubs), feeds the scan cache's exact evictions, and
offers the same ``since`` gap contract so a future remote retained
frontend can long-poll it exactly like ``repl_inval``.
"""

from __future__ import annotations

import threading
from collections import deque
from itertools import islice
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils import topic as topic_util
from ..utils.hlc import HLC
from ..utils.metrics import REPLICATION


class RetainedScanCache:
    """Per-tenant LRU of retained-scan results with exact invalidation."""

    def __init__(self, *, max_keys_per_tenant: int = 512,
                 max_tenants: int = 4096) -> None:
        self.max_keys_per_tenant = max_keys_per_tenant
        self.max_tenants = max_tenants
        # tenant -> {(filter_levels, limit): (topics tuple, token)}
        self._d: Dict[str, dict] = {}
        self._seq: Dict[str, int] = {}
        self._gen = 0   # wholesale epoch: folded into every token so a
        # reset-raced in-flight scan can never store a stale entry
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.bumps = 0

    def token(self, tenant: str):
        """Pre-scan snapshot: a mutation landing while the scan is in
        flight bumps the seq, so the late store is refused."""
        return (self._gen, self._seq.get(tenant, 0))

    def get(self, tenant: str, key, limit: Optional[int]):
        t = self._d.get(tenant)
        if t is None:
            self.misses += 1
            return None
        v = t.get((key, limit))
        if v is None:
            self.misses += 1
            return None
        # true LRU: refresh recency (dict preserves insertion order)
        del t[(key, limit)]
        t[(key, limit)] = v
        self.hits += 1
        return v[0]

    def put(self, tenant: str, key, limit: Optional[int], topics,
            token) -> None:
        if (self._gen, self._seq.get(tenant, 0)) != token:
            return      # a mutation raced this scan: instantly stale
        t = self._d.get(tenant)
        if t is None:
            if len(self._d) >= self.max_tenants:
                return  # bounded tenant cardinality: never grow past it
            t = self._d[tenant] = {}
        if len(t) >= self.max_keys_per_tenant:
            drop = len(t) // 2
            for k in list(islice(iter(t), drop)):
                del t[k]
            self.evictions += drop
        t[(key, limit)] = (tuple(topics), token)

    # ---------------- invalidation ------------------------------------------

    def on_delta(self, tenant: Optional[str], topic_levels, op: str) -> None:
        """The index delta hook: evict exactly the cached filters the
        mutated topic matches. ``tenant=None`` (reset / stream loss)
        degrades to a wholesale clear."""
        if tenant is None:
            self.bump_all()
            return
        # the seq bump must precede the key scan: an in-flight scan that
        # walked PRE-mutation tables may store after this hook ran, and
        # only the token mismatch defeats it
        self._seq[tenant] = self._seq.get(tenant, 0) + 1
        t = self._d.get(tenant)
        if not t:
            return
        levels = list(topic_levels or ())
        dead = [k for k in t
                if topic_util.matches(levels, list(k[0]))]
        for k in dead:
            del t[k]
        self.evictions += len(dead)

    def bump(self, tenant: str) -> None:
        self._seq[tenant] = self._seq.get(tenant, 0) + 1
        if self._d.pop(tenant, None) is not None:
            self.bumps += 1

    def bump_all(self) -> None:
        self._gen += 1
        self._d.clear()
        self.bumps += 1

    def snapshot(self) -> dict:
        return {"tenants": len(self._d),
                "keys": sum(len(t) for t in self._d.values()),
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions, "bumps": self.bumps}


class RetainedDeltaLog:
    """Bounded seq'd ring of retained mutations for ONE retain range —
    the retained twin of the route ``DeltaLog`` (records are lean
    ``(seq, hlc, tenant, topic, op)`` tuples: retained deltas carry no
    patch plans, the consumer contract is exact invalidation)."""

    def __init__(self, origin: str, range_id: str, cap: int = 8192) -> None:
        self.origin = origin
        self.range_id = range_id
        self.epoch = int(HLC.physical(HLC.INST.get()) // 1000) & 0x3FFFFFFF
        self.next_seq = 1
        self._records: deque = deque(maxlen=cap)
        self._lock = threading.Lock()
        from ..replication import register_hub
        register_hub(self)

    def append(self, tenant: str, topic_levels: Sequence[str],
               op: str) -> None:
        with self._lock:
            self._records.append(
                (self.next_seq, HLC.INST.get(), tenant,
                 tuple(topic_levels), op))
            self.next_seq += 1
        REPLICATION.inc("records")
        # ISSUE 18 lag plane: the RetainedStandby applies under the same
        # fixed "retained" stream key
        from ..obs.lag import LAG
        LAG.note_emit("retained", "retained")

    def since(self, after_seq: int) -> Tuple[str, List[tuple]]:
        with self._lock:
            head = self.next_seq - 1
            if after_seq > head:
                return "gap", []
            if after_seq == head:
                return "ok", []
            oldest = self.next_seq - len(self._records)
            if after_seq + 1 < oldest:
                return "gap", []
            start = after_seq + 1 - oldest
            return "ok", list(islice(self._records, start, None))

    def status(self) -> dict:
        # same row shape as the route ReplicationHub (one-range list):
        # GET /replication consumers iterate hubs uniformly
        with self._lock:
            return {"role": "retained-hub", "origin": self.origin,
                    "ranges": [{"range": self.range_id,
                                "epoch": self.epoch,
                                "head_seq": self.next_seq - 1,
                                "ring": len(self._records)}]}
