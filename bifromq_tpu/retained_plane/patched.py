"""PatchableRetainedIndex arenas (ISSUE 13 tentpole part 1).

``RetainedPatchableTrie`` extends the ISSUE 9 :class:`PatchableTrie`
with in-place maintenance of the retained-mode columns the match walk
never reads — the columns PR 9 left compaction-refreshed:

- **child-list runs** (``NODE_CSTART``/``NODE_CCOUNT``): the retained
  walk's '+' expansion reads each node's contiguous child slice, so a
  patch-inserted literal child appends into the run's slack or relocates
  the run to the child-arena tail with doubled capacity (amortized O(1)
  per insert; the abandoned run becomes garbage the next compaction
  reclaims). '$'-prefixed children insert at the FRONT so the
  sys-children-are-a-prefix invariant ([MQTT-4.7.2-1] root skip,
  ``NODE_SYS_CCOUNT``) survives patching.
- **subtree slot ranges** (``NODE_SUB_RCOUNT``/``NODE_SYS_SLOTS``): the
  '#' emission depends on compile-time pre-order slot contiguity, which
  no in-place insert can preserve — so these stay FROZEN for base-era
  slots (still exact: removals tombstone in place, host expansion
  filters) and patch-era topics ride a separate **extras plane**:
  ``ext_tab[node] = (extra_start, extra_count, own_idx, ·)`` into an
  append-only ``extra_list`` of slot ids. A new topic's slot id is
  appended to the extra run of its node and every ancestor (amortized
  O(depth) per insert via capacity-doubling run relocation), the device
  walk emits each '#'-node's extra run next to its base range, and the
  final-level step emits ``own_idx`` (the node's own patch slot) next
  to the base ``(RSTART, RCOUNT)`` pair. Base and extras are disjoint
  by construction, so no dedup pass exists anywhere.

Set/clear/expire therefore cost row writes + at most O(depth)
run-relocations — never a ``compile_tries`` rebuild. A retained flood
leaves exactly the same narrow-scatter device traffic profile as
subscription churn does on the forward matcher; full compilation
survives only as fragmentation-triggered compaction.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..models.automaton import (
    _EMPTY, EXT_COLS, EXT_COUNT, EXT_OWN, EXT_START, NODE_CCOUNT,
    NODE_CSTART, NODE_RCOUNT, NODE_RSTART, NODE_SUB_RCOUNT,
    NODE_SYS_CCOUNT, CompiledTrie, PatchableTrie, PatchFallback,
    _next_pow2, level_hash,
)
from ..utils import topic as topic_util


class RetainedPatchableTrie(PatchableTrie):
    """A PatchableTrie whose arenas accept in-place RETAINED-TOPIC
    patches (concrete topics only — wildcards are invalid in topics, so
    descent is purely literal and the '+'/'#' pointer columns stay
    empty by construction)."""

    def __init__(self, ct: CompiledTrie) -> None:
        super().__init__(ct)
        self._init_retained()

    def _init_retained(self) -> None:
        cap = int(self.node_tab.shape[0])
        # extras plane: per-node (start, count, own_idx) + the slot-id list
        ext = np.full((cap, EXT_COLS), 0, dtype=np.int32)
        ext[:, EXT_OWN] = _EMPTY
        self.ext_tab = ext
        self.extra_list = np.full(64, _EMPTY, dtype=np.int32)
        self.extra_live = 0
        self.extra_garbage = 0
        # child-list arena: base CSR runs + growth headroom at the tail
        base_cl = self.child_list
        # the match-plane pad (PatchableTrie pow2-floors child_list) is
        # dead tail, not live CSR data — size the retained arena from the
        # real run length so appends land right after the base runs
        used = int(getattr(self, "child_used", base_cl.shape[0]))
        ccap = _next_pow2(max(used + 1, int(used * 1.25)), floor=16)
        cl = np.full(ccap, _EMPTY, dtype=np.int32)
        cl[:used] = base_cl[:used]
        self.child_list = cl
        self.child_live = used
        self.child_garbage = 0
        # host-only run capacities (device only ever reads (start, count))
        self._child_cap: Dict[int, int] = {}
        self._ext_cap: Dict[int, int] = {}
        # patch-era own slots per node (base own slots live in the node
        # record; these live in the extras plane)
        self._own_slot: Dict[int, int] = {}
        self._roots: Set[int] = set(self.tenant_root.values())
        # dirty tracking for the three retained-only tables
        self._dirty_ext: Set[int] = set()
        self._dirty_child: Set[int] = set()
        self._dirty_extra: Set[int] = set()

    def install_retained_extras(self, *, ext_tab: np.ndarray,
                                extra_list: np.ndarray, extra_live: int,
                                extra_garbage: int, child_live: int,
                                child_garbage: int,
                                child_cap: Dict[int, int],
                                ext_cap: Dict[int, int],
                                own_slot: Dict[int, int]) -> None:
        """Install a leader's retained extras VERBATIM (ISSUE 16
        standby resync) — the retained-plane counterpart of
        :meth:`PatchableTrie.from_arenas`. The instance must come from
        ``RetainedPatchableTrie.from_arenas(...)`` (which skips
        ``_init_retained``); this supplies the half ``from_arenas``
        cannot: the extras plane, run capacities and patch-era own
        slots, byte-identical to the leader so subsequent op-replays
        land on the same rows."""
        self.ext_tab = np.asarray(ext_tab, dtype=np.int32)
        self.extra_list = np.asarray(extra_list, dtype=np.int32)
        self.extra_live = int(extra_live)
        self.extra_garbage = int(extra_garbage)
        # base child_list was installed by from_arenas — the shipped
        # arena already carries the leader's grown capacity + slack
        self.child_live = int(child_live)
        self.child_garbage = int(child_garbage)
        self._child_cap = dict(child_cap)
        self._ext_cap = dict(ext_cap)
        self._own_slot = dict(own_slot)
        self._roots = set(self.tenant_root.values())
        self._dirty_ext = set()
        self._dirty_child = set()
        self._dirty_extra = set()

    # ---------------- arena growth ------------------------------------------

    def _grow_nodes(self) -> None:
        cap = self.node_tab.shape[0]
        super()._grow_nodes()
        ext = np.full((cap * 2, EXT_COLS), 0, dtype=np.int32)
        ext[:, EXT_OWN] = _EMPTY
        ext[:cap] = self.ext_tab
        self.ext_tab = ext
        self._full.add("ext")
        self._dirty_ext.clear()

    def _alloc_node(self) -> int:
        nid = super()._alloc_node()
        # retained-mode zeroing: a fresh node owns no base subtree slots
        # (its topics live in the extras plane), so the '#'-range count
        # must read 0, not the _EMPTY sentinel
        self.node_tab[nid, NODE_SUB_RCOUNT] = 0
        return nid

    def _child_alloc(self, n: int) -> int:
        need = self.child_live + n
        if need > self.child_list.shape[0]:
            ncap = _next_pow2(need, floor=self.child_list.shape[0] * 2)
            cl = np.full(ncap, _EMPTY, dtype=np.int32)
            cl[:self.child_live] = self.child_list[:self.child_live]
            self.child_list = cl
            self._full.add("child")
            self._dirty_child.clear()
        start = self.child_live
        self.child_live = need
        return start

    def _extra_alloc(self, n: int) -> int:
        need = self.extra_live + n
        if need > self.extra_list.shape[0]:
            ncap = _next_pow2(need, floor=self.extra_list.shape[0] * 2)
            el = np.full(ncap, _EMPTY, dtype=np.int32)
            el[:self.extra_live] = self.extra_list[:self.extra_live]
            self.extra_list = el
            self._full.add("extra")
            self._dirty_extra.clear()
        start = self.extra_live
        self.extra_live = need
        return start

    # ---------------- dirty bookkeeping -------------------------------------

    def _mark_child(self, idx: int, n: int = 1) -> None:
        if "child" not in self._full:
            self._dirty_child.update(range(idx, idx + n))

    def _mark_extra(self, idx: int, n: int = 1) -> None:
        if "extra" not in self._full:
            self._dirty_extra.update(range(idx, idx + n))

    def _mark_ext(self, nid: int) -> None:
        if "ext" not in self._full:
            self._dirty_ext.add(int(nid))

    @property
    def dirty(self) -> bool:
        return bool(super().dirty or self._dirty_ext or self._dirty_child
                    or self._dirty_extra)

    def drain_dirty_retained(self):
        """(full names, node rows, edge buckets, ext rows, child idx,
        extra idx, ops) since the last drain — the retained flush's
        superset of :meth:`PatchableTrie.drain_dirty`."""
        def _vec(s):
            return np.fromiter(sorted(s), dtype=np.int64, count=len(s))
        ext, child, extra = (_vec(self._dirty_ext), _vec(self._dirty_child),
                             _vec(self._dirty_extra))
        self._dirty_ext = set()
        self._dirty_child = set()
        self._dirty_extra = set()
        full, nodes, edges, ops = self.drain_dirty()
        return full, nodes, edges, ext, child, extra, ops

    def restore_dirty(self, ops: int) -> None:
        super().restore_dirty(ops)
        self._full |= {"child", "ext", "extra"}
        self._dirty_ext.clear()
        self._dirty_child.clear()
        self._dirty_extra.clear()

    def frag_pending(self) -> bool:
        if super().frag_pending():
            return True
        from ..models.automaton import patch_frag_floor, patch_frag_ratio
        garbage = self.extra_garbage + self.child_garbage
        return garbage >= patch_frag_floor() and garbage >= \
            patch_frag_ratio() * max(1, self.extra_live + self.child_live)

    def patch_stats(self) -> Dict[str, object]:
        out = super().patch_stats()
        out.update({
            "extra_live": int(self.extra_live),
            "extra_garbage": int(self.extra_garbage),
            "child_live": int(self.child_live),
            "child_garbage": int(self.child_garbage),
            "patched_own_slots": len(self._own_slot),
        })
        return out

    # ---------------- run machinery -----------------------------------------

    def _append_child(self, parent: int, cid: int, level: str) -> None:
        """Insert ``cid`` into ``parent``'s child run, preserving the
        sys-children-prefix invariant ('$'-children insert at the
        FRONT). Relocates the run to the arena tail with doubled
        capacity when full (or when a front-insert is needed and the
        run cannot shift in place — base runs have no slack at all)."""
        is_sys = level.startswith(topic_util.SYS_PREFIX)
        cstart = int(self.node_tab[parent, NODE_CSTART])
        ccount = int(self.node_tab[parent, NODE_CCOUNT])
        cap = self._child_cap.get(parent, ccount if cstart >= 0 else 0)
        if ccount == 0:
            start = self._child_alloc(4)
            self.child_list[start] = cid
            self._child_cap[parent] = 4
            self.node_tab[parent, NODE_CSTART] = start
            self._mark_child(start)
        elif not is_sys and ccount < cap:
            self.child_list[cstart + ccount] = cid
            self._mark_child(cstart + ccount)
        else:
            ncap = max(4, 2 * (ccount + 1))
            start = self._child_alloc(ncap)
            run = self.child_list[cstart:cstart + ccount].copy()
            if is_sys:
                self.child_list[start] = cid
                self.child_list[start + 1:start + 1 + ccount] = run
            else:
                self.child_list[start:start + ccount] = run
                self.child_list[start + ccount] = cid
            self._child_cap[parent] = ncap
            self.node_tab[parent, NODE_CSTART] = start
            self.child_garbage += ccount
            self._mark_child(start, ccount + 1)
        self.node_tab[parent, NODE_CCOUNT] = ccount + 1
        if is_sys:
            self.node_tab[parent, NODE_SYS_CCOUNT] = \
                max(0, int(self.node_tab[parent, NODE_SYS_CCOUNT])) + 1
        self._mark_node(parent)

    def _ext_append(self, nid: int, slot: int, *, own: bool = False) -> None:
        """Append ``slot`` to ``nid``'s extras run (capacity-doubling
        relocation on overflow); ``own=True`` also records the entry's
        index in EXT_OWN for the final-level emission."""
        start = int(self.ext_tab[nid, EXT_START])
        count = int(self.ext_tab[nid, EXT_COUNT])
        cap = self._ext_cap.get(nid, 0)
        if count >= cap:
            ncap = max(8, 2 * cap)
            s = self._extra_alloc(ncap)
            if count:
                self.extra_list[s:s + count] = \
                    self.extra_list[start:start + count]
                self.extra_garbage += count
                own_idx = int(self.ext_tab[nid, EXT_OWN])
                if own_idx >= 0:
                    # the run moved; the own-slot entry moved with it
                    self.ext_tab[nid, EXT_OWN] = s + (own_idx - start)
            self._ext_cap[nid] = ncap
            start = s
            self.ext_tab[nid, EXT_START] = start
            self._mark_extra(start, count)
        self.extra_list[start + count] = slot
        self._mark_extra(start + count)
        self.ext_tab[nid, EXT_COUNT] = count + 1
        if own:
            self.ext_tab[nid, EXT_OWN] = start + count
        self._mark_ext(nid)

    # ---------------- descent -----------------------------------------------

    def _descend_retained(self, root: int, levels: Sequence[str],
                          create: bool) -> Tuple[int, bool]:
        """Literal-only descent; returns (node, created_any). A patch-era
        same-parent 64-bit hash collision raises PatchFallback (the
        caller schedules a re-salting rebuild — the compiler's exactness
        contract, never a guess)."""
        nid = root
        created = False
        for level in levels:
            h1, h2 = level_hash(level, self.salt)
            child = self._edge_child(nid, h1, h2)
            if child >= 0:
                known = self._edge_level.get((nid, h1, h2))
                if known is not None and known != level:
                    raise PatchFallback(
                        f"level-hash collision {known!r} vs {level!r}")
            else:
                if not create:
                    return _EMPTY, created
                child = self._alloc_node()
                self._edge_insert(nid, h1, h2, child)
                self._edge_level[(nid, h1, h2)] = level
                self._append_child(nid, child, level)
                self.parent[child] = nid
                created = True
            nid = child
        return nid, created

    # ---------------- the retained patch ops --------------------------------

    def _base_own_slot(self, nid: int) -> Optional[int]:
        rs = int(self.node_tab[nid, NODE_RSTART])
        rc = int(self.node_tab[nid, NODE_RCOUNT])
        return rs if rc > 0 else None

    def retained_add(self, tenant_id: str, levels: Sequence[str],
                     route) -> Tuple[str, int]:
        """Fold one retained SET into the arenas. Returns
        ``("exists"|"resurrect"|"add", slot)`` — "exists" when the topic
        is already live (payload replacement, index unchanged),
        "resurrect" when a tombstoned slot came back in place (zero
        device traffic), "add" when a fresh slot appended (extras plane
        updated for the node + every ancestor)."""
        if not levels:
            raise PatchFallback("empty retained topic")
        root = self.tenant_root.get(tenant_id, _EMPTY)
        if root < 0:
            root = self._alloc_node()
            self.tenant_root[tenant_id] = root
            self._roots.add(root)
        nid, _created = self._descend_retained(root, levels, create=True)
        base_s = self._base_own_slot(nid)
        if base_s is not None:
            if self._kind[base_s] != CompiledTrie.SLOT_DEAD:
                return "exists", base_s
            # base-era tombstone resurrection: the slot's matching IS
            # this topic (receiver == topic by construction), so flipping
            # the kind back restores base-range coverage exactly — no
            # device write at all (kinds are host-side)
            self._kind[base_s] = CompiledTrie.SLOT_NORMAL
            self.dead_slots = max(0, self.dead_slots - 1)
            self.patched_ops += 1
            return "resurrect", base_s
        own = self._own_slot.get(nid)
        if own is not None:
            if self._kind[own] != CompiledTrie.SLOT_DEAD:
                return "exists", own
            self._kind[own] = CompiledTrie.SLOT_NORMAL
            self.dead_slots = max(0, self.dead_slots - 1)
            self.patched_ops += 1
            return "resurrect", own
        slot = self._append_slot(route)
        self._own_slot[nid] = slot
        # extras: the node's own run records the slot as EXT_OWN (the
        # final-level emission), every ancestor's run carries it for the
        # '#'-subtree emission. [MQTT-4.7.2-1]: a '$'-rooted topic never
        # enters the TENANT ROOT's run — the root-level '#'/'+' skip.
        sys_topic = levels[0].startswith(topic_util.SYS_PREFIX)
        anc = nid
        first = True
        while anc >= 0:
            if not (sys_topic and anc == root):
                self._ext_append(anc, slot, own=first)
            first = False
            if anc == root:
                break
            anc = int(self.parent[anc])
        self.patched_ops += 1
        self._pending_ops += 1
        return "add", slot

    def retained_remove(self, tenant_id: str,
                        levels: Sequence[str]) -> bool:
        """Fold one retained CLEAR/EXPIRE in: tombstone the topic's slot
        (base-era or patch-era) — zero device traffic, reclaimed by the
        next fragmentation compaction."""
        root = self.tenant_root.get(tenant_id, _EMPTY)
        if root < 0:
            return False
        nid, _created = self._descend_retained(root, levels, create=False)
        if nid < 0:
            return False
        s = self._base_own_slot(nid)
        if s is None or self._kind[s] == CompiledTrie.SLOT_DEAD:
            s = self._own_slot.get(nid)
        if s is None or self._kind[s] == CompiledTrie.SLOT_DEAD:
            return False
        self._kind[s] = CompiledTrie.SLOT_DEAD
        self.dead_slots += 1
        self.patched_ops += 1
        self._pending_ops += 1
        return True

    @property
    def pristine(self) -> bool:
        """True when no patch-era slots or tombstones exist — the state
        in which base subtree ranges alone are exhaustive and exact (the
        native escalation walker and range-level ``limit`` clipping are
        only valid here)."""
        return self.extra_live == 0 and self.dead_slots == 0

    def expansion_budget(self) -> int:
        """Upper bound on dead slots any single emitted range set can
        contain — the ``limit`` head-room the expander adds before
        host-side dead filtering trims back down."""
        return int(self.dead_slots)

    # the forward-matcher patch entry points make no sense on a retained
    # trie (routes are concrete topics); refuse loudly rather than
    # silently corrupting the extras invariants
    def patch_add(self, *a, **kw):  # pragma: no cover - guard
        raise PatchFallback("retained trie: use retained_add")

    def patch_remove(self, *a, **kw):  # pragma: no cover - guard
        raise PatchFallback("retained trie: use retained_remove")


__all__ = ["RetainedPatchableTrie", "EXT_START", "EXT_COUNT", "EXT_OWN",
           "EXT_COLS"]
