"""Retained & persistent-session serving plane (ISSUE 13).

The paper reuses the compiled trie kernel for retain-store's wildcard
lookup, but until this package the reproduction's retained side was a
cold subsystem: every RETAIN mutation re-ran ``compile_tries`` over the
whole topic population and SUBSCRIBE-time scans ran a bare synchronous
dispatch outside every resilience/observability plane built since PR 6.
This package promotes it to a first-class device-resident serving plane:

- :mod:`patched` — :class:`RetainedPatchableTrie`: RETAIN set/clear/
  expire become in-place arena patches. The retained-mode columns the
  forward match walk never reads (NODE_CSTART/NODE_CCOUNT child-list
  runs, NODE_SYS_CCOUNT sys prefixes) are maintained incrementally;
  the frozen pre-order subtree ranges (NODE_SUB_RCOUNT/NODE_SYS_SLOTS)
  stay exact for base-era slots while patch-era topics ride a separate
  per-node **extras** plane (``ext_tab`` + ``extra_list``) the device
  walk reads next to the base ranges — TrieJax's relational framing
  again: the delta of a trie under concrete-topic inserts is a handful
  of orderable row writes, never a rebuild.
- :mod:`scan` — :class:`RetainedScanPlane`: device-side wildcard
  retained scans on SUBSCRIBE served through the shared dispatch-ring /
  device-breaker / watchdog machinery (``retain.scan`` span + stage,
  oracle degradation on timeout/breaker-open) with a filter-keyed
  result cache evicted EXACTLY by retained deltas.
- :mod:`cache` — :class:`RetainedScanCache`: the filter-keyed result
  cache + :class:`RetainedDeltaLog`, the seq'd per-range retained delta
  stream (same gap/wholesale-bump degradation contract as the PR 12
  route stream; surfaces under ``GET /replication``).
- :mod:`drain` — :class:`DrainGovernor`: tenant-fair admission for
  offline-inbox drain storms at reconnect (``inbox.drain`` span +
  stage), so a mass reconnect cannot let one tenant's backlog monopolize
  the broker.
"""

from __future__ import annotations

from .cache import RetainedDeltaLog, RetainedScanCache
from .drain import DrainGovernor
from .patched import RetainedPatchableTrie
from .scan import RetainedScanPlane

__all__ = [
    "RetainedPatchableTrie", "RetainedScanPlane", "RetainedScanCache",
    "RetainedDeltaLog", "DrainGovernor",
]
