"""Tenant-fair admission for offline-inbox drain storms (ISSUE 13
tentpole part 3, drain half).

A mass reconnect (broker restart, network partition heal) wakes
thousands of persistent sessions at once, and every one of them starts
draining its offline backlog through the inbox store — consensus reads,
send-path work and ack windows all at once. Untamed, the biggest
tenant's reconnect herd monopolizes the broker exactly when it is most
fragile. ``DrainGovernor`` bounds the storm with the same
:class:`~bifromq_tpu.resilience.device.BoundedSlots` machinery that
bounds the dispatch ring and the QoS1 ingest gate:

- a **global** slot pool (``BIFROMQ_DRAIN_SLOTS``) caps concurrent
  catch-up drains process-wide,
- a **per-tenant** pool (``BIFROMQ_DRAIN_PER_TENANT``) caps any one
  tenant's share of it, so tenant B's two reconnects never wait behind
  tenant A's two thousand,
- tenants currently flagged by the PR 3 noisy-neighbor detector yield
  one scheduling beat before queuing while other drains are waiting —
  quiet tenants' sessions reach the global pool first under pressure.

The governed section is the persistent session's CATCH-UP drain (the
first fetch burst after attach — ``inbox.drain`` span + stage,
mqtt/persistent.py); steady-state wakes are cheap and bypass.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, Optional

from ..resilience.device import BoundedSlots
from ..utils.env import env_int

_NOISY_YIELD_S = 0.005


def drain_slots() -> int:
    """Concurrent catch-up drains admitted process-wide."""
    return max(1, env_int("BIFROMQ_DRAIN_SLOTS", 64))


def drain_per_tenant() -> int:
    """One tenant's cap on those slots."""
    return max(1, env_int("BIFROMQ_DRAIN_PER_TENANT", 8))


class _DrainSlot:
    """``async with governor.slot(tenant):`` — acquires tenant-then-
    global (one fixed order; both pools are plain BoundedSlots)."""

    __slots__ = ("gov", "tenant", "_held", "_gate")

    def __init__(self, gov: "DrainGovernor", tenant: str) -> None:
        self.gov = gov
        self.tenant = tenant
        self._held = False
        self._gate = None

    async def __aenter__(self):
        gov = self.gov
        if gov.noisy_fn(self.tenant) and gov._global.waiting > 0:
            # pressure + a noisy tenant: yield one beat so quiet
            # tenants' drains enqueue ahead of the herd
            gov.deferred_total += 1
            await asyncio.sleep(_NOISY_YIELD_S)
        t0 = time.perf_counter()
        # pin the gate OBJECT for the slot's lifetime: the governor's
        # cardinality sweep may drop/recreate map entries meanwhile
        self._gate = gov._tenant_gate(self.tenant)
        await self._gate.acquire()
        try:
            await gov._global.acquire()
        except BaseException:
            self._gate.release()
            raise
        self._held = True
        gov.admitted_total += 1
        gov.wait_s_total += time.perf_counter() - t0
        return self

    async def __aexit__(self, *exc):
        if self._held:
            self._held = False
            self.gov._global.release()
            self._gate.release()
            d = self.gov.drained_by_tenant
            d[self.tenant] = d.get(self.tenant, 0) + 1
            if len(d) > 4096:
                for k in list(d)[:2048]:
                    del d[k]
        return False


def drain_shed_margin() -> float:
    """How much quieter (in occupancy units) the quietest peer must be
    before a saturated broker sheds a reconnect toward it."""
    from ..utils.env import env_float
    return env_float("BIFROMQ_DRAIN_SHED_MARGIN", 0.5)


class DrainGovernor:
    def __init__(self, *, slots: Optional[int] = None,
                 per_tenant: Optional[int] = None,
                 noisy_fn=None) -> None:
        # env knobs resolve lazily at first use (R3 discipline); explicit
        # ctor values stay pinned
        self._slots = slots
        self._per_tenant = per_tenant
        self._global_pool: Optional[BoundedSlots] = None
        self._tenants: Dict[str, BoundedSlots] = {}
        if noisy_fn is None:
            def noisy_fn(tenant: str) -> bool:
                from ..obs import OBS
                return OBS.is_noisy(tenant)
        self.noisy_fn = noisy_fn
        self.admitted_total = 0
        self.deferred_total = 0
        self.wait_s_total = 0.0
        # ISSUE 15 satellite (ROADMAP retained follow-up (d)): cluster-
        # aware reconnect shedding. The broker wires this to the gossip
        # view's peer_drain_pressures(); a standalone governor (None)
        # never sheds.
        self.peer_pressure_fn = None   # () -> Dict[node, float] | None
        self.shed_to_peers_total = 0
        # per-tenant completed-drain totals, served by snapshot() (top
        # slice) and bounded: past 4096 tenants the coldest half drops
        self.drained_by_tenant: Dict[str, int] = {}
        from ..obs import OBS
        OBS.register_drain_governor(self)   # /metrics "retained" section

    @property
    def _global(self) -> BoundedSlots:
        if self._global_pool is None:
            self._global_pool = BoundedSlots(
                self._slots if self._slots is not None else drain_slots())
        return self._global_pool

    def _tenant_gate(self, tenant: str) -> BoundedSlots:
        gate = self._tenants.get(tenant)
        if gate is None:
            if len(self._tenants) > 16384:
                # bounded cardinality: drop idle gates (an in-flight
                # drain holds its gate object via the slot, not the map)
                self._tenants = {t: g for t, g in self._tenants.items()
                                 if g.in_flight or g.waiting}
            cap = (self._per_tenant if self._per_tenant is not None
                   else drain_per_tenant())
            gate = self._tenants[tenant] = BoundedSlots(cap)
        return gate

    def slot(self, tenant: str) -> _DrainSlot:
        return _DrainSlot(self, tenant)

    def pressure(self) -> float:
        """Drain occupancy: (active + queued) / global slots. >= 1.0
        means every slot is busy; > 1.0 means reconnects are parking.
        Gossiped in the health digest (ObsHub.drain_pressure)."""
        g = self._global
        return (g.in_flight + g.waiting) / max(1, g.capacity)

    def should_shed_reconnect(self) -> bool:
        """Consult the cluster BEFORE admitting a herd drain (ISSUE 15
        satellite, ROADMAP retained follow-up (d)): when this broker's
        drain pool is saturated AND some fresh peer gossips materially
        lower drain pressure, refuse the reconnect so the client's retry
        lands on the quieter peer. Standalone (no gossip wiring) or
        cluster-wide saturation never sheds — refusing with nowhere
        better to go just adds a reconnect loop."""
        fn = self.peer_pressure_fn
        if fn is None:
            return False
        local = self.pressure()
        if local < 1.0:
            return False
        try:
            peers = fn() or {}
        except Exception:  # noqa: BLE001 — gossip must not break CONNECT
            return False
        if not peers:
            return False
        if min(peers.values()) + drain_shed_margin() <= local:
            self.shed_to_peers_total += 1
            return True
        return False

    def snapshot(self) -> dict:
        g = self._global
        top = sorted(self.drained_by_tenant.items(),
                     key=lambda kv: -kv[1])[:5]
        return {"active": g.in_flight, "waiting": g.waiting,
                "capacity": g.capacity,
                "pressure": round(self.pressure(), 3),
                "admitted_total": self.admitted_total,
                "deferred_total": self.deferred_total,
                "shed_to_peers_total": self.shed_to_peers_total,
                "avg_wait_ms": round(
                    1e3 * self.wait_s_total
                    / max(1, self.admitted_total), 3),
                "tenants_active": sum(
                    1 for g in self._tenants.values() if g.in_flight),
                "drained_by_tenant_top": dict(top)}
