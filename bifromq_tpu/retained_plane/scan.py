"""Device-side retained wildcard scans on SUBSCRIBE, served through the
shared resilience machinery (ISSUE 13 tentpole part 2).

``RetainedScanPlane`` wraps one replica's :class:`RetainedIndex` with
the same serving discipline the forward matcher earned over PRs 6–11:

- the extras-aware walk dispatches through a bounded
  :class:`~bifromq_tpu.models.pipeline.DispatchRing` (scan N+1 preps
  while scan N walks; ring gauges feed ``queue_pressure``),
- readiness is awaited under the ISSUE 7 watchdog — a hung device
  RECLAIMS the slot (orphaned result arrays quarantined) and degrades
  THIS scan to the exact host oracle (``match_filter_host``),
- a per-plane device circuit breaker (shared board — ``/metrics``
  ``fabric.breakers``, gossip digest demotion) opens on repeated
  timeouts/errors: open means scans skip dispatch entirely; half-open
  admits ONE canary scan that re-closes only on oracle parity,
- results memoize in a filter-keyed :class:`RetainedScanCache` whose
  evictions are EXACT, fed per-mutation by the retained delta hooks,
- every batch lands a ``retain.scan`` span + stage sample and the
  per-tenant latency/fanout feed ``TenantSLO`` (the ISSUE 13 satellite
  bugfix: retained scans used to bypass the RED windows entirely).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .. import trace
from ..utils.env import env_bool
from ..utils.metrics import STAGES
from .cache import RetainedScanCache

log = logging.getLogger(__name__)


def scan_async_enabled() -> bool:
    """Kill-switch for the async retained scan plane
    (``BIFROMQ_RETAIN_SCAN_ASYNC=0`` serves scans synchronously —
    still cached, still SLO-fed, no ring/watchdog overlap)."""
    return env_bool("BIFROMQ_RETAIN_SCAN_ASYNC", True)


def scan_cache_enabled() -> bool:
    """Kill-switch for the filter-keyed scan result cache
    (``BIFROMQ_RETAIN_SCAN_CACHE=0``)."""
    return env_bool("BIFROMQ_RETAIN_SCAN_CACHE", True)


class RetainedScanPlane:
    """One replica's retained-scan serving plane.

    ``index_fn`` indirects to the live :class:`RetainedIndex` — the
    hosting coproc REPLACES its index on reset-from-KV, and a plane
    pinning the old object would serve a dead world.
    """

    def __init__(self, index_fn: Callable, *, device=None,
                 cache: Optional[RetainedScanCache] = None) -> None:
        self._index_fn = index_fn
        self.device = device
        self._ring = None
        from ..resilience.device import (DEVICE_BREAKERS,
                                         device_breaker_enabled)
        self.device_breaker = (DEVICE_BREAKERS.create()
                               if device_breaker_enabled() else None)
        self.cache = cache if cache is not None else (
            RetainedScanCache() if scan_cache_enabled() else None)
        self.scans_total = 0
        self.degraded_total: Dict[str, int] = {}
        from ..obs import OBS
        OBS.register_retained_plane(self)   # /metrics "retained" section

    @property
    def index(self):
        return self._index_fn()

    def _pipeline_ring(self):
        if self._ring is None:
            from ..models.pipeline import DispatchRing
            self._ring = DispatchRing()
            from ..obs import OBS
            OBS.device.register_ring(self._ring)
        return self._ring

    # ---------------- serving entry points ----------------------------------

    def scan_batch_sync(self, queries: Sequence[Tuple[str, Sequence[str]]],
                        limit: Optional[int] = None) -> List[List[str]]:
        """The non-async leg (no event loop / kill-switch): same cache,
        spans, SLO feeds — minus the ring overlap and the watchdog."""
        return self._serve(queries, limit, self._device_serve_sync)

    async def scan_batch(self, queries: Sequence[Tuple[str, Sequence[str]]],
                         limit: Optional[int] = None) -> List[List[str]]:
        """Pipelined serving path: the device walk dispatches through
        the bounded ring and is awaited on READINESS under the watchdog;
        breaker-open / timeout / device-error serve the exact oracle."""
        if not scan_async_enabled():
            return self.scan_batch_sync(queries, limit)
        out = self._serve(queries, limit, None)
        if isinstance(out, list):
            return out
        miss_queries, fill = out
        rows, reason = await self._device_serve_async(miss_queries, limit)
        return fill(rows, reason)

    def _serve(self, queries, limit, device_leg):
        """Shared front-end: cache probe + span/stage/SLO accounting.
        With ``device_leg`` None (the async caller), returns a
        ``(miss_queries, fill)`` continuation tuple instead of
        blocking (a plain list means the serve completed)."""
        if not queries:
            return []
        t0 = time.perf_counter()
        self.scans_total += len(queries)
        cache = self.cache
        out: List[Optional[List[str]]] = [None] * len(queries)
        miss_rows: List[int] = []
        tokens: Dict[str, object] = {}
        for qi, (tenant, levels) in enumerate(queries):
            key = tuple(levels)
            hit = cache.get(tenant, key, limit) if cache is not None \
                else None
            if hit is not None:
                out[qi] = list(hit)
            else:
                miss_rows.append(qi)
                if cache is not None and tenant not in tokens:
                    tokens[tenant] = cache.token(tenant)
        miss_queries = [queries[qi] for qi in miss_rows]
        front_s = time.perf_counter() - t0
        miss_set = set(miss_rows)

        def fill(rows, reason):
            for qi, row in zip(miss_rows, rows):
                out[qi] = row
                if cache is not None and reason is None:
                    tenant, levels = queries[qi]
                    cache.put(tenant, tuple(levels), limit, row,
                              tokens[tenant])
            dt = time.perf_counter() - t0
            STAGES.record("retain.scan", dt)
            with trace.span("retain.scan", n_queries=len(queries),
                            misses=len(miss_rows), limit=limit) as sp:
                if reason is not None:
                    self.degraded_total[reason] = \
                        self.degraded_total.get(reason, 0) + 1
                    if sp is not trace.NOOP:
                        sp.set_tag("degraded", reason)
            # ISSUE 13 satellite bugfix: retained scans feed the tenant
            # RED windows like deliver.fanout does — latency per scanned
            # tenant, achieved retained fan-out into the fanout share.
            # Attribution is per-QUERY cost: a cache hit records the
            # front-probe time, not the batch's device-leg wall (these
            # windows feed the noisy detector, which also gates drain
            # admission — a quiet tenant co-batched with a heavy one
            # must not inherit its latency)
            from ..obs import OBS
            for qi, (tenant, _lv) in enumerate(queries):
                OBS.record_latency(tenant, "retain.scan",
                                   dt if qi in miss_set else front_s)
                OBS.record_fanout(tenant, len(out[qi] or ()))
            return [row if row is not None else [] for row in out]

        if device_leg is None:
            if not miss_queries:
                return fill([], None)
            return miss_queries, fill
        rows, reason = (device_leg(miss_queries, limit)
                        if miss_queries else ([], None))
        return fill(rows, reason)

    # ---------------- device legs -------------------------------------------

    def _oracle_rows(self, queries, limit) -> List[List[str]]:
        idx = self.index
        out = []
        for tenant, levels in queries:
            trie = idx.tries.get(tenant)
            out.append(match_filter_host_safe(trie, levels, limit))
        return out

    def _canary_parity(self, queries, rows, limit) -> Tuple[bool, list]:
        """Half-open success bar: the canary scan's device rows must be
        an exact (limit-aware) subset of the unbounded host oracle — a
        device returning plausible-but-wrong topics after a fault must
        NOT re-close the breaker."""
        full = self._oracle_rows(queries, None)
        ok = True
        for row, want in zip(rows, full):
            wset = set(want)
            bound = len(want) if limit is None else min(limit, len(want))
            if len(row) != bound or not set(row) <= wset:
                ok = False
                break
        if limit is None:
            return ok, full
        return ok, [w[:limit] for w in full]

    def _device_serve_sync(self, queries, limit):
        verdict = self._admit()
        if verdict == "rejected":
            return self._degrade(queries, limit, "breaker")
        try:
            idx = self.index
            prep = idx.prepare_scan(queries)
            prep, res = idx.dispatch_scan(prep)
            return self._settle(queries, limit, idx, prep, res,
                                verdict=verdict)
        except Exception as e:  # noqa: BLE001 — degrade, don't fail
            if self.device_breaker is not None:
                self.device_breaker.record_failure(repr(e))
            return self._degrade(queries, limit, "device_error", e)

    def _admit(self) -> str:
        br = self.device_breaker
        return br.admit() if br is not None else "ok"

    async def _device_serve_async(self, queries, limit):
        from ..resilience.device import DeviceTimeoutError
        verdict = self._admit()
        if verdict == "rejected":
            return self._degrade(queries, limit, "breaker")
        ring = self._pipeline_ring()
        settled = False
        try:
            idx = self.index
            idx.serving_ring = ring     # ring-less flushers must see us
            prep = idx.prepare_scan(queries)
            await ring.acquire()
            try:
                prep, res = idx.dispatch_scan(prep, ring=ring, own_slots=1)
                ring.start_fetch(res)
                try:
                    await ring.wait_ready(res)
                except DeviceTimeoutError:
                    ring.reclaim(res)
                    raise
                except BaseException:
                    # cancelled mid-wait: the arrays may still be in
                    # flight — park them like a timeout does
                    ring.quarantine.add(res)
                    raise
            finally:
                ring.release()
            rows, reason = self._settle(queries, limit, idx, prep, res,
                                        verdict=verdict)
            settled = True
            return rows, reason
        except DeviceTimeoutError as e:
            from ..utils.metrics import FABRIC, FabricMetric
            FABRIC.inc(FabricMetric.DEVICE_TIMEOUT)
            if self.device_breaker is not None:
                self.device_breaker.record_failure(repr(e))
                settled = True
            return self._degrade(queries, limit, "timeout")
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — degrade, don't fail
            if self.device_breaker is not None:
                self.device_breaker.record_failure(repr(e))
                settled = True
            return self._degrade(queries, limit, "device_error", e)
        finally:
            if self.device_breaker is not None and verdict == "canary" \
                    and not settled:
                # cancelled mid-probe with no verdict: the half-open
                # budget must not leak or the breaker wedges refusing
                self.device_breaker.release_probe()

    def _settle(self, queries, limit, idx, prep, res, *, verdict):
        """Fetch + expand, then the breaker bookkeeping (canary scans
        re-close only on oracle parity)."""
        if verdict == "rejected":
            return self._degrade(queries, limit, "breaker")
        rows = idx.expand_scan(prep, idx.fetch_scan(res), limit=limit)
        br = self.device_breaker
        if br is not None:
            if verdict == "canary":
                ok, oracle_rows = self._canary_parity(queries, rows, limit)
                if not ok:
                    br.record_failure("canary row parity")
                    return self._degrade(queries, limit, "canary_parity",
                                         rows_override=oracle_rows)
                br.record_success()
            elif br.state == "closed":
                # pre-trip straggler guard (same as the forward matcher)
                br.record_success()
        return rows, None

    def _degrade(self, queries, limit, reason, exc=None,
                 rows_override=None):
        if exc is not None:
            log.warning("retained scan failed; serving host oracle: %r",
                        exc)
        from ..utils.metrics import FABRIC, FabricMetric
        FABRIC.inc(FabricMetric.MATCH_DEGRADED, len(queries))
        rows = (rows_override if rows_override is not None
                else self._oracle_rows(queries, limit))
        return rows, reason

    def snapshot(self) -> dict:
        out = {"scans_total": self.scans_total,
               "degraded": dict(self.degraded_total)}
        if self.cache is not None:
            out["cache"] = self.cache.snapshot()
        if self.device_breaker is not None:
            out["breaker"] = self.device_breaker.state
        return out


def match_filter_host_safe(trie, levels, limit) -> List[str]:
    from ..models.retained import match_filter_host
    if trie is None:
        return []
    return match_filter_host(trie, list(levels), limit=limit)
