"""Causal CRDT lattices: dot context, AWORSet, MVReg, ORMap.

Re-expression of base-crdt's causal CRDT core (base-crdt-store
.../basecrdt/core/api + internal: AWORSet, ORMap, MVReg with dot-store
lattices, SURVEY.md §2.3). State is (dot store, causal context); merge is
the standard causal join:

    keep (dot → value) entries present in BOTH states, plus entries present
    in ONE state whose dot the other's context has NOT seen (fresh), drop
    the rest (observed-removed); then join the contexts.

All mutators are DELTA mutators: they return a small state containing just
the new/retracted dots, suitable for delta anti-entropy (store.py).
Serialization is plain JSON-able dicts so deltas ride the gossip messenger.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

Dot = Tuple[str, int]


class DotContext:
    """Compact causal context: version vector + dot cloud (≈ the reference's
    causal context with compaction)."""

    def __init__(self) -> None:
        self.vv: Dict[str, int] = {}
        self.cloud: Set[Dot] = set()

    def contains(self, dot: Dot) -> bool:
        rid, n = dot
        return n <= self.vv.get(rid, 0) or dot in self.cloud

    def add(self, dot: Dot) -> None:
        self.cloud.add(dot)
        self.compact()

    def next_dot(self, replica_id: str) -> Dot:
        n = self.vv.get(replica_id, 0) + 1
        self.vv[replica_id] = n
        return (replica_id, n)

    def compact(self) -> None:
        changed = True
        while changed:
            changed = False
            for dot in list(self.cloud):
                rid, n = dot
                if n == self.vv.get(rid, 0) + 1:
                    self.vv[rid] = n
                    self.cloud.discard(dot)
                    changed = True
                elif n <= self.vv.get(rid, 0):
                    self.cloud.discard(dot)
                    changed = True

    def join(self, other: "DotContext") -> None:
        for rid, n in other.vv.items():
            self.vv[rid] = max(self.vv.get(rid, 0), n)
        self.cloud |= other.cloud
        self.compact()

    def to_dict(self) -> dict:
        return {"vv": dict(self.vv), "cloud": sorted(self.cloud)}

    @staticmethod
    def from_dict(d: dict) -> "DotContext":
        ctx = DotContext()
        ctx.vv = {k: int(v) for k, v in d.get("vv", {}).items()}
        ctx.cloud = {(r, int(n)) for r, n in d.get("cloud", [])}
        return ctx


class _DotStoreCRDT:
    """Shared join logic for dot-keyed stores (AWORSet / MVReg)."""

    def __init__(self) -> None:
        self.ctx = DotContext()
        self.store: Dict[Dot, Any] = {}

    def join(self, other: "_DotStoreCRDT") -> bool:
        """Causal join; returns True if local state changed."""
        changed = False
        for dot, val in list(self.store.items()):
            if dot not in other.store and other.ctx.contains(dot):
                del self.store[dot]  # observed-removed elsewhere
                changed = True
        for dot, val in other.store.items():
            if dot not in self.store and not self.ctx.contains(dot):
                self.store[dot] = val  # fresh
                changed = True
        before = (dict(self.ctx.vv), set(self.ctx.cloud))
        self.ctx.join(other.ctx)
        if (self.ctx.vv, self.ctx.cloud) != before:
            changed = True
        return changed

    def to_dict(self) -> dict:
        return {"ctx": self.ctx.to_dict(),
                "store": [[list(dot), val] for dot, val in
                          sorted(self.store.items())]}

    @classmethod
    def from_dict(cls, d: dict):
        o = cls()
        o.ctx = DotContext.from_dict(d.get("ctx", {}))
        o.store = {(r, int(n)): val for (r, n), val in d.get("store", [])}
        return o


class AWORSet(_DotStoreCRDT):
    """Add-wins observed-remove set (≈ AWORSet.java)."""

    def add(self, replica_id: str, element) -> "AWORSet":
        """Add (re-tagging any same-element dots); returns the delta."""
        retired = [dot for dot, v in self.store.items() if v == element]
        dot = self.ctx.next_dot(replica_id)
        for d in retired:
            del self.store[d]
        self.store[dot] = element
        delta = AWORSet()
        delta.store[dot] = element
        delta.ctx.add(dot)
        for d in retired:
            delta.ctx.add(d)
        delta.ctx.compact()
        return delta

    def remove(self, element) -> "AWORSet":
        """Observed-remove: retract every dot carrying the element."""
        retired = [dot for dot, v in self.store.items() if v == element]
        delta = AWORSet()
        for d in retired:
            del self.store[d]
            delta.ctx.add(d)
        delta.ctx.compact()
        return delta

    def elements(self) -> List:
        seen = []
        for _, v in sorted(self.store.items()):
            if v not in seen:
                seen.append(v)
        return seen

    def __contains__(self, element) -> bool:
        return any(v == element for v in self.store.values())


class MVReg(_DotStoreCRDT):
    """Multi-value register (≈ MVReg.java): concurrent writes all survive
    until causally overwritten."""

    def write(self, replica_id: str, value) -> "MVReg":
        retired = list(self.store)
        dot = self.ctx.next_dot(replica_id)
        self.store.clear()
        self.store[dot] = value
        delta = MVReg()
        delta.store[dot] = value
        delta.ctx.add(dot)
        for d in retired:
            delta.ctx.add(d)
        delta.ctx.compact()
        return delta

    def values(self) -> List:
        return [v for _, v in sorted(self.store.items())]


class ORMap:
    """Observed-remove map of key → embedded causal CRDT
    (≈ ORMap.java: values are themselves CRDTs sharing the map context).

    Implemented as key-partitioned sub-CRDTs; a key removal retracts every
    dot of its sub-CRDT. Deltas are per-key sub-deltas.
    """

    def __init__(self, value_type=AWORSet) -> None:
        self.value_type = value_type
        self.entries: Dict[str, Any] = {}

    def get(self, key: str):
        e = self.entries.get(key)
        if e is None:
            e = self.entries[key] = self.value_type()
        return e

    def keys(self) -> List[str]:
        return sorted(k for k, v in self.entries.items() if v.store)

    def remove_key(self, key: str) -> Optional[dict]:
        """Retract the whole sub-CRDT; returns the delta dict or None."""
        e = self.entries.get(key)
        if e is None or not e.store:
            return None
        delta = self.value_type()
        for dot in list(e.store):
            del e.store[dot]
            delta.ctx.add(dot)
        delta.ctx.compact()
        return {key: delta.to_dict()}

    def join(self, deltas: Dict[str, dict]) -> bool:
        changed = False
        for key, sub in deltas.items():
            if self.get(key).join(self.value_type.from_dict(sub)):
                changed = True
        return changed

    def to_dict(self) -> Dict[str, dict]:
        return {k: v.to_dict() for k, v in self.entries.items()}

    def delta_for(self, key: str) -> Dict[str, dict]:
        return {key: self.get(key).to_dict()}


class RWORSet(_DotStoreCRDT):
    """Remove-wins observed-remove set (≈ RWORSet.java): a concurrent
    add || remove of the same element resolves to REMOVED. Dots carry
    (element, is_add) pairs; an element is present iff it has at least
    one live add-dot and NO live remove-dot."""

    def add(self, replica_id: str, element) -> "RWORSet":
        retired = [d for d, (el, _k) in self.store.items() if el == element]
        dot = self.ctx.next_dot(replica_id)
        for d in retired:
            del self.store[d]
        self.store[dot] = (element, True)
        delta = RWORSet()
        delta.store[dot] = (element, True)
        delta.ctx.add(dot)
        for d in retired:
            delta.ctx.add(d)
        delta.ctx.compact()
        return delta

    def remove(self, replica_id: str, element) -> "RWORSet":
        """Remove leaves a live remove-dot (the wins marker), unlike
        AWORSet's pure retraction."""
        retired = [d for d, (el, _k) in self.store.items() if el == element]
        dot = self.ctx.next_dot(replica_id)
        for d in retired:
            del self.store[d]
        self.store[dot] = (element, False)
        delta = RWORSet()
        delta.store[dot] = (element, False)
        delta.ctx.add(dot)
        for d in retired:
            delta.ctx.add(d)
        delta.ctx.compact()
        return delta

    def __contains__(self, element) -> bool:
        has_add = has_rm = False
        for el, is_add in self.store.values():
            if el == element:
                if is_add:
                    has_add = True
                else:
                    has_rm = True
        return has_add and not has_rm

    def elements(self) -> List:
        seen = []
        for _, (el, _k) in sorted(self.store.items()):
            if el not in seen and el in self:
                seen.append(el)
        return seen

    @classmethod
    def from_dict(cls, d: dict):
        o = super().from_dict(d)
        o.store = {dot: tuple(v) for dot, v in o.store.items()}
        return o


class EWFlag(_DotStoreCRDT):
    """Enable-wins flag (≈ EWFlagOperation.java): concurrent
    enable || disable resolves to ENABLED (the enable's fresh dot
    survives the disable's observed retraction)."""

    def enable(self, replica_id: str) -> "EWFlag":
        retired = list(self.store)
        dot = self.ctx.next_dot(replica_id)
        for d in retired:
            del self.store[d]
        self.store[dot] = True
        delta = EWFlag()
        delta.store[dot] = True
        delta.ctx.add(dot)
        for d in retired:
            delta.ctx.add(d)
        delta.ctx.compact()
        return delta

    def disable(self) -> "EWFlag":
        retired = list(self.store)
        delta = EWFlag()
        for d in retired:
            del self.store[d]
            delta.ctx.add(d)
        delta.ctx.compact()
        return delta

    def read(self) -> bool:
        return bool(self.store)


class DWFlag(_DotStoreCRDT):
    """Disable-wins flag (≈ DWFlagOperation.java): the dual of EWFlag —
    dots mark DISABLED, so a concurrent disable survives an enable's
    retraction and the flag reads disabled."""

    def disable(self, replica_id: str) -> "DWFlag":
        retired = list(self.store)
        dot = self.ctx.next_dot(replica_id)
        for d in retired:
            del self.store[d]
        self.store[dot] = False
        delta = DWFlag()
        delta.store[dot] = False
        delta.ctx.add(dot)
        for d in retired:
            delta.ctx.add(d)
        delta.ctx.compact()
        return delta

    def enable(self) -> "DWFlag":
        retired = list(self.store)
        delta = DWFlag()
        for d in retired:
            del self.store[d]
            delta.ctx.add(d)
        delta.ctx.compact()
        return delta

    def read(self) -> bool:
        return not self.store


class CCounter(_DotStoreCRDT):
    """Causal counter (≈ CCounterOperation.java): each replica's
    contribution rides ONE dot; increments re-tag the replica's dot with
    the accumulated value, and zero() causally retracts every observed
    contribution (concurrent increments survive a reset — add-wins)."""

    def _own(self, replica_id: str) -> int:
        return sum(v for (r, _n), v in self.store.items()
                   if r == replica_id)

    def inc(self, replica_id: str, n: int = 1) -> "CCounter":
        retired = [d for d in self.store if d[0] == replica_id]
        total = self._own(replica_id) + n
        dot = self.ctx.next_dot(replica_id)
        for d in retired:
            del self.store[d]
        self.store[dot] = total
        delta = CCounter()
        delta.store[dot] = total
        delta.ctx.add(dot)
        for d in retired:
            delta.ctx.add(d)
        delta.ctx.compact()
        return delta

    def zero(self) -> "CCounter":
        retired = list(self.store)
        delta = CCounter()
        for d in retired:
            del self.store[d]
            delta.ctx.add(d)
        delta.ctx.compact()
        return delta

    def read(self) -> int:
        return sum(self.store.values())
