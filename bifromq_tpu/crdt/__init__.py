from .core import AWORSet, DotContext, MVReg, ORMap  # noqa: F401
from .store import AntiEntropy, CRDTStore, InMemMessenger  # noqa: F401
