"""CRDT store + delta anti-entropy over a messenger.

Re-expression of base-crdt-store's replication plane (CRDTStore.java:54
hosting replicas; AntiEntropy.java:44 running delta-sync rounds with
neighbors over the cluster messenger):

- ``CRDTStore.host(uri)`` binds a named ORMap replica.
- Every local mutation appends its delta to a bounded delta log; an
  ``AntiEntropy`` round sends each neighbor the log suffix it has not
  acked yet (delta sync), falling back to FULL state when the neighbor is
  too far behind the truncated log — the reference's delta/state dual.
- Transport is pluggable: ``InMemMessenger`` for in-process clusters
  (partition-able, the reference's test-cluster trick) and
  ``AgentMessenger`` riding the gossip host's UDP socket.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Callable, Dict, List, Optional, Tuple

from .core import AWORSet, MVReg, ORMap

log = logging.getLogger(__name__)

MAX_DELTA_LOG = 256


class IMessenger:
    """Fire-and-forget peer messaging + neighbor discovery."""

    def send(self, to: str, payload: dict) -> None:
        raise NotImplementedError

    def neighbors(self) -> List[str]:
        raise NotImplementedError

    def on_receive(self, cb: Callable[[str, dict], None]) -> None:
        raise NotImplementedError


class InMemMessenger(IMessenger):
    """In-process fabric with partitions (≈ CRDTStoreTestCluster)."""

    def __init__(self) -> None:
        self.nodes: Dict[str, Callable[[str, dict], None]] = {}
        self.blocked: set = set()
        self._me: Optional[str] = None

    def bind(self, node_id: str) -> "InMemMessenger":
        m = InMemMessenger()
        m.nodes = self.nodes
        m.blocked = self.blocked
        m._me = node_id
        m._root = self if getattr(self, "_root", None) is None else self._root
        return m

    def partition(self, *groups) -> None:
        self.blocked.clear()
        gl = [set(g) for g in groups]
        everyone = set(self.nodes)
        for g in gl:
            for a in g:
                for b in everyone - g:
                    self.blocked.add(frozenset((a, b)))

    def heal(self) -> None:
        self.blocked.clear()

    def send(self, to: str, payload: dict) -> None:
        if frozenset((self._me, to)) in self.blocked:
            return
        cb = self.nodes.get(to)
        if cb is not None:
            cb(self._me, json.loads(json.dumps(payload)))

    def neighbors(self) -> List[str]:
        return sorted(n for n in self.nodes if n != self._me)

    def on_receive(self, cb: Callable[[str, dict], None]) -> None:
        self.nodes[self._me] = cb


class AgentMessenger(IMessenger):
    """CRDT messenger riding the gossip host's UDP socket (the reference's
    anti-entropy-over-cluster-messenger layering, AntiEntropy.java:44 over
    base-cluster Messenger): peers = alive gossip members."""

    CHANNEL = "crdt"

    def __init__(self, agent_host) -> None:
        self.agent_host = agent_host
        self._cb: Optional[Callable[[str, dict], None]] = None
        agent_host.register_payload_handler(
            self.CHANNEL, lambda sender, data: self._cb
            and self._cb(sender, data))

    def send(self, to: str, payload: dict) -> None:
        self.agent_host.send_payload(to, self.CHANNEL, payload)

    def neighbors(self) -> List[str]:
        return sorted(n for n in self.agent_host.alive_members()
                      if n != self.agent_host.node_id)

    def on_receive(self, cb: Callable[[str, dict], None]) -> None:
        self._cb = cb


class _Replica:
    """One hosted ORMap replica with a delta log."""

    def __init__(self, uri: str, replica_id: str) -> None:
        self.uri = uri
        self.replica_id = replica_id
        self.ormap = ORMap()
        # delta log: seq -> per-key delta dict (bounded; older rounds fall
        # back to full-state sync)
        self.delta_log: List[Tuple[int, Dict[str, dict]]] = []
        self.next_seq = 1
        self.first_seq = 1
        self._watchers: List[Callable[[], None]] = []

    def record_delta(self, delta: Dict[str, dict]) -> None:
        self.delta_log.append((self.next_seq, delta))
        self.next_seq += 1
        if len(self.delta_log) > MAX_DELTA_LOG:
            dropped = len(self.delta_log) - MAX_DELTA_LOG
            self.delta_log = self.delta_log[dropped:]
            self.first_seq = self.delta_log[0][0]

    def watch(self, cb: Callable[[], None]) -> None:
        self._watchers.append(cb)

    def notify(self) -> None:
        for cb in self._watchers:
            try:
                cb()
            except Exception:  # noqa: BLE001
                log.exception("crdt watcher failed")


class CRDTStore:
    """Hosts replicas; applies local mutations; answers sync messages."""

    def __init__(self, replica_id: str, messenger: IMessenger) -> None:
        self.replica_id = replica_id
        self.messenger = messenger
        self.replicas: Dict[str, _Replica] = {}
        messenger.on_receive(self._on_message)

    def host(self, uri: str) -> _Replica:
        r = self.replicas.get(uri)
        if r is None:
            r = self.replicas[uri] = _Replica(uri, self.replica_id)
        return r

    # ---------------- local mutations (delta mutators) ---------------------

    def set_add(self, uri: str, key: str, element) -> None:
        r = self.host(uri)
        delta = r.ormap.get(key).add(self.replica_id, element)
        r.record_delta({key: delta.to_dict()})
        r.notify()

    def set_remove(self, uri: str, key: str, element) -> None:
        r = self.host(uri)
        delta = r.ormap.get(key).remove(element)
        r.record_delta({key: delta.to_dict()})
        r.notify()

    def remove_key(self, uri: str, key: str) -> None:
        r = self.host(uri)
        delta = r.ormap.remove_key(key)
        if delta is not None:
            r.record_delta(delta)
            r.notify()

    def elements(self, uri: str, key: str) -> List:
        return self.host(uri).ormap.get(key).elements()

    def keys(self, uri: str) -> List[str]:
        return self.host(uri).ormap.keys()

    # ---------------- sync protocol ----------------------------------------
    # {t: "delta", uri, from_seq, to_seq, deltas: [...]}   + implicit ack req
    # {t: "full", uri, state}
    # {t: "ack",  uri, seq}

    def _on_message(self, sender: str, msg: dict) -> None:
        t = msg.get("t")
        uri = msg.get("uri")
        if t == "delta":
            r = self.host(uri)
            changed = False
            for delta in msg["deltas"]:
                if r.ormap.join(delta):
                    changed = True
            self.messenger.send(sender, {"t": "ack", "uri": uri,
                                         "seq": msg["to_seq"]})
            if changed:
                r.notify()
        elif t == "full":
            r = self.host(uri)
            if r.ormap.join(msg["state"]):
                r.notify()
            self.messenger.send(sender, {"t": "ack", "uri": uri,
                                         "seq": msg["seq"]})
        elif t == "ack":
            ae = getattr(self, "_anti_entropy", None)
            if ae is not None:
                ae.on_ack(sender, uri, int(msg["seq"]))


class AntiEntropy:
    """Periodic delta-sync rounds with every neighbor (AntiEntropy.java:44).

    Tracks the highest seq each neighbor acked per uri; a round ships the
    unacked delta-log suffix, or full state if the suffix fell off the
    bounded log (or the neighbor is brand new)."""

    def __init__(self, store: CRDTStore, *, interval: float = 0.05) -> None:
        self.store = store
        self.interval = interval
        self.acked: Dict[Tuple[str, str], int] = {}   # (peer, uri) -> seq
        self._task: Optional[asyncio.Task] = None
        store._anti_entropy = self

    def on_ack(self, peer: str, uri: str, seq: int) -> None:
        key = (peer, uri)
        self.acked[key] = max(self.acked.get(key, 0), seq)

    def run_round(self) -> None:
        for uri, r in self.store.replicas.items():
            for peer in self.store.messenger.neighbors():
                # -1 = never acked: forces one initial full-state exchange,
                # after which ack(next_seq-1) silences the pair until the
                # next local mutation
                acked = self.acked.get((peer, uri), -1)
                if acked >= r.next_seq - 1:
                    continue  # fully caught up
                if acked + 1 < r.first_seq:
                    # suffix unavailable (or nothing logged): full state
                    self.store.messenger.send(peer, {
                        "t": "full", "uri": uri,
                        "state": r.ormap.to_dict(),
                        "seq": r.next_seq - 1})
                else:
                    deltas = [d for s, d in r.delta_log if s > acked]
                    if not deltas:
                        continue
                    self.store.messenger.send(peer, {
                        "t": "delta", "uri": uri,
                        "from_seq": acked + 1, "to_seq": r.next_seq - 1,
                        "deltas": deltas})

    async def start(self) -> None:
        async def loop():
            while True:
                self.run_round()
                await asyncio.sleep(self.interval)
        self._task = asyncio.create_task(loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except BaseException:  # noqa: BLE001
                pass
            self._task = None
