"""Shared value types (≈ reference bifromq-common-type protos).

These mirror the semantics of the reference protos without protobuf: they are
frozen dataclasses used across the broker plane. The match plane (models/ops)
works on integer-packed tensors derived from these.

Reference protos:
- RouteMatcher   bifromq-common-type/src/main/proto/commontype/RouteMatcher.proto:27
- ClientInfo     .../commontype/ClientInfo.proto
- QoS            .../commontype/QoS.proto
- Message/TopicMessagePack  .../commontype/TopicMessage.proto
- MatchInfo      .../commontype/SubInfo.proto
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .utils import topic as topic_util


class QoS(enum.IntEnum):
    AT_MOST_ONCE = 0
    AT_LEAST_ONCE = 1
    EXACTLY_ONCE = 2


class RouteMatcherType(enum.IntEnum):
    """RouteMatcher.Type (RouteMatcher.proto:28-32)."""
    NORMAL = 0
    UNORDERED_SHARE = 1
    ORDERED_SHARE = 2


@dataclass(frozen=True)
class RouteMatcher:
    """A parsed subscription topic filter (RouteMatcher.proto:27).

    ``filter_levels`` excludes the ``$share/<group>`` / ``$oshare/<group>``
    prefix; ``mqtt_topic_filter`` preserves the original filter string.
    """
    type: RouteMatcherType
    filter_levels: Tuple[str, ...]
    mqtt_topic_filter: str
    group: Optional[str] = None

    @staticmethod
    def from_topic_filter(topic_filter: str) -> "RouteMatcher":
        """Build from a validated MQTT topic filter string.

        Mirrors reference RouteMatcher construction at subscription time
        (bifromq-mqtt .../MQTTSessionHandler and TopicUtil.from helpers).
        """
        if topic_util.is_unordered_shared(topic_filter):
            rest = topic_filter[len(topic_util.UNORDERED_SHARE) + 1:]
            group, _, real_filter = rest.partition(topic_util.DELIMITER)
            return RouteMatcher(
                type=RouteMatcherType.UNORDERED_SHARE,
                filter_levels=tuple(topic_util.parse(real_filter)),
                mqtt_topic_filter=topic_filter,
                group=group,
            )
        if topic_util.is_ordered_shared(topic_filter):
            rest = topic_filter[len(topic_util.ORDERED_SHARE) + 1:]
            group, _, real_filter = rest.partition(topic_util.DELIMITER)
            return RouteMatcher(
                type=RouteMatcherType.ORDERED_SHARE,
                filter_levels=tuple(topic_util.parse(real_filter)),
                mqtt_topic_filter=topic_filter,
                group=group,
            )
        return RouteMatcher(
            type=RouteMatcherType.NORMAL,
            filter_levels=tuple(topic_util.parse(topic_filter)),
            mqtt_topic_filter=topic_filter,
        )

    @property
    def is_shared(self) -> bool:
        return self.type != RouteMatcherType.NORMAL


@dataclass(frozen=True)
class ClientInfo:
    """Identity of a connected client (ClientInfo.proto)."""
    tenant_id: str
    type: str = "MQTT"
    metadata: Tuple[Tuple[str, str], ...] = ()

    def meta(self) -> Dict[str, str]:
        return dict(self.metadata)


@dataclass(frozen=True)
class Message:
    """A published application message (TopicMessage.proto Message)."""
    message_id: int
    pub_qos: QoS
    payload: bytes
    timestamp: int  # HLC stamp
    expiry_seconds: int = 0xFFFFFFFF
    is_retain: bool = False
    is_retained: bool = False  # delivered because it was a retained message
    user_properties: Tuple[Tuple[str, str], ...] = ()
    content_type: str = ""
    response_topic: str = ""
    correlation_data: bytes = b""
    payload_format_indicator: int = 0


@dataclass(frozen=True)
class PublisherMessagePack:
    publisher: ClientInfo
    messages: Tuple[Message, ...]


@dataclass(frozen=True)
class TopicMessagePack:
    """Messages grouped by topic (TopicMessage.proto TopicMessagePack)."""
    topic: str
    packs: Tuple[PublisherMessagePack, ...]


@dataclass(frozen=True)
class MatchInfo:
    """A matched delivery target (SubInfo.proto MatchInfo)."""
    matcher: RouteMatcher
    receiver_id: str
    incarnation: int = 0


@dataclass(frozen=True)
class TopicFilterOption:
    """Per-subscription options recorded by inbox/session (TopicFilterOption.proto)."""
    qos: QoS = QoS.AT_MOST_ONCE
    retain_as_published: bool = False
    no_local: bool = False
    retain_handling: int = 0
    sub_id: Optional[int] = None
    incarnation: int = 0


def now_millis() -> int:
    return int(time.time() * 1000)
