"""Cross-node session dictionary (≈ bifromq-session-dict).

The in-broker ``SessionRegistry`` kicks same-(tenant, client) owners
locally; this service extends the contract cluster-wide over the RPC
fabric (SessionDictService.proto kill/exist/get semantics):

- ``SessionDictRPCService`` exposes a broker's live registry (exist /
  kill / client list) as the ``session-dict`` fabric service.
- ``SessionDictClient`` fans a kick out to every peer broker when a
  client id connects here (the reference's register-stream kick,
  SessionRegistry.java:72-86 across nodes), and answers online checks
  (≈ OnlineCheckScheduler/BatchSessionExistCall).
"""

from __future__ import annotations

import asyncio
import logging
import struct
from typing import List, Tuple

from ..rpc.fabric import RPCServer, ServiceRegistry, _len16, _read16

log = logging.getLogger(__name__)

SERVICE = "session-dict"


class SessionDictRPCService:
    def __init__(self, broker) -> None:
        self.broker = broker

    def register(self, server: RPCServer) -> None:
        server.register(SERVICE, {
            "kill": self._kill,
            "exist": self._exist,
            "clients": self._clients,
            "sub": self._sub,
            "unsub": self._unsub,
            "inbox_state": self._inbox_state,
        })

    async def _kill(self, payload: bytes, okey: str) -> bytes:
        tenant_b, pos = _read16(payload, 0)
        client_b, pos = _read16(payload, pos)
        session = self.broker.session_registry.get(tenant_b.decode(),
                                                   client_b.decode())
        if session is None:
            return b"\x00"
        await session.kick()
        return b"\x01"

    async def _exist(self, payload: bytes, okey: str) -> bytes:
        tenant_b, pos = _read16(payload, 0)
        (n,) = struct.unpack_from(">H", payload, pos)
        pos += 2
        out = bytearray()
        for _ in range(n):
            client_b, pos = _read16(payload, pos)
            s = self.broker.session_registry.get(tenant_b.decode(),
                                                 client_b.decode())
            out.append(1 if s is not None and not s.closed else 0)
        return bytes(out)

    async def _clients(self, payload: bytes, okey: str) -> bytes:
        tenant_b, _ = _read16(payload, 0)
        ids = self.broker.session_registry.client_ids(tenant_b.decode())
        out = bytearray(struct.pack(">H", len(ids)))
        for cid in ids:
            out += _len16(cid.encode())
        return bytes(out)

    # on-behalf management surface (≈ SessionDictService.proto sub/unsub/
    # inboxState): operate on a LIVE session hosted by this broker
    async def _sub(self, payload: bytes, okey: str) -> bytes:
        tenant_b, pos = _read16(payload, 0)
        client_b, pos = _read16(payload, pos)
        tf_b, pos = _read16(payload, pos)
        (qos,) = struct.unpack_from(">B", payload, pos)
        session = self.broker.session_registry.get(tenant_b.decode(),
                                                   client_b.decode())
        if session is None or session.closed:
            return _len16(b"no_session")
        res = await session.admin_sub(tf_b.decode(), qos)
        return _len16(res.encode())

    async def _unsub(self, payload: bytes, okey: str) -> bytes:
        tenant_b, pos = _read16(payload, 0)
        client_b, pos = _read16(payload, pos)
        tf_b, pos = _read16(payload, pos)
        session = self.broker.session_registry.get(tenant_b.decode(),
                                                   client_b.decode())
        if session is None or session.closed:
            return _len16(b"no_session")
        res = await session.admin_unsub(tf_b.decode())
        return _len16(res.encode())

    async def _inbox_state(self, payload: bytes, okey: str) -> bytes:
        import json
        tenant_b, pos = _read16(payload, 0)
        client_b, pos = _read16(payload, pos)
        session = self.broker.session_registry.get(tenant_b.decode(),
                                                   client_b.decode())
        if session is None or session.closed:
            return _len16(b"")
        return _len16(json.dumps(session.inbox_state()).encode())


class SessionDictClient:
    """Broker-side client: cluster-wide kick + online checks.

    ``self_address`` is REQUIRED (this broker's own session-dict RPC
    address): without it the broker would kick the session it just
    registered via its own service.
    """

    PEER_TIMEOUT = 2.0   # a sick peer must not stall CONNECT

    def __init__(self, registry: ServiceRegistry, *,
                 self_address: str) -> None:
        if not self_address:
            raise ValueError("self_address is required")
        self.registry = registry
        self.self_address = self_address

    async def _call_peer(self, ep: str, method: str,
                         payload: bytes, order_key: str = "") -> bytes:
        from ..resilience.policy import (DEFAULT_RETRY_POLICY,
                                         is_idempotent)
        from ..rpc.fabric import (RPCCircuitOpenError, RPCTimeoutError,
                                  RPCTransportError)
        attempt = 0
        while True:
            attempt += 1
            try:
                return await self.registry.client_for(ep).call(
                    SERVICE, method, payload, order_key=order_key,
                    timeout=self.PEER_TIMEOUT)
            except (RPCTimeoutError, RPCCircuitOpenError):
                # a peer that sat silent for a full PEER_TIMEOUT window
                # (or whose breaker will deterministically refuse again)
                # gains nothing from a same-peer re-send; fail fast — the
                # invariant PEER_TIMEOUT exists to protect CONNECT
                raise
            except RPCTransportError:
                # whitelisted reads (exist/clients/inbox_state) retry the
                # SAME peer briefly on dial/connection-loss blips — a
                # transient drop must not report a live session as
                # offline; mutations (kill/sub/unsub) fail fast and the
                # caller's fan-out semantics handle it
                if not is_idempotent(SERVICE, method) \
                        or not DEFAULT_RETRY_POLICY.should_retry(attempt):
                    raise
                from ..utils.metrics import FABRIC, FabricMetric
                FABRIC.inc(FabricMetric.RPC_RETRIES)
                await asyncio.sleep(DEFAULT_RETRY_POLICY.backoff(attempt))

    async def kick_everywhere(self, tenant_id: str, client_id: str) -> int:
        """Kick (tenant, client) on every peer broker concurrently;
        returns the kick count. Called when a client id registers here, so
        the cluster holds ONE live session per (tenant, client)."""
        payload = _len16(tenant_id.encode()) + _len16(client_id.encode())
        peers = [ep for ep in self.registry.endpoints(SERVICE)
                 if ep != self.self_address]
        if not peers:
            return 0
        outs = await asyncio.gather(
            *(self._call_peer(ep, "kill", payload,
                              order_key=f"{tenant_id}/{client_id}")
              for ep in peers),
            return_exceptions=True)
        kicked = 0
        for ep, out in zip(peers, outs):
            if isinstance(out, BaseException):
                log.debug("session-dict kick to %s failed: %r", ep, out)
            else:
                kicked += out[0]
        return kicked

    async def inbox_state(self, tenant_id: str, client_id: str):
        """Live-session state lookup (≈ inboxState); None if not online."""
        import json
        payload = _len16(tenant_id.encode()) + _len16(client_id.encode())
        body = await self._on_behalf_raw("inbox_state", tenant_id,
                                         client_id, payload,
                                         miss=b"")
        return json.loads(body.decode()) if body else None

    async def sub(self, tenant_id: str, client_id: str, tf: str,
                  qos: int) -> str:
        """Subscribe on behalf of a live session wherever it is hosted
        (≈ SessionDictService.sub). Returns a SubReply.Result name."""
        payload = (_len16(tenant_id.encode()) + _len16(client_id.encode())
                   + _len16(tf.encode()) + struct.pack(">B", qos))
        out = await self._on_behalf_raw("sub", tenant_id, client_id,
                                        payload, miss=b"no_session")
        return out.decode()

    async def unsub(self, tenant_id: str, client_id: str, tf: str) -> str:
        """Unsubscribe on behalf of a live session (≈ unsub)."""
        payload = (_len16(tenant_id.encode()) + _len16(client_id.encode())
                   + _len16(tf.encode()))
        out = await self._on_behalf_raw("unsub", tenant_id, client_id,
                                        payload, miss=b"no_session")
        return out.decode()

    async def _on_behalf_raw(self, method: str, tenant_id: str,
                             client_id: str, payload: bytes, *,
                             miss: bytes) -> bytes:
        """Fan the call to PEER brokers concurrently (the caller has
        already checked its own registry; self is excluded like
        kick_everywhere); at most one broker hosts the session, so at
        most one answer differs from ``miss``."""
        peers = [ep for ep in self.registry.endpoints(SERVICE)
                 if ep != self.self_address]
        if not peers:
            return miss
        outs = await asyncio.gather(
            *(self._call_peer(ep, method, payload,
                              order_key=f"{tenant_id}/{client_id}")
              for ep in peers),
            return_exceptions=True)
        for ep, out in zip(peers, outs):
            if isinstance(out, BaseException):
                log.debug("session-dict %s to %s failed: %r",
                          method, ep, out)
                continue
            body, _ = _read16(out, 0)
            if body != miss:
                return body
        return miss

    async def exist(self, tenant_id: str,
                    client_ids: List[str]) -> List[bool]:
        """Cluster-wide online check (any broker hosting it counts)."""
        alive = [False] * len(client_ids)
        payload = bytearray(_len16(tenant_id.encode()))
        payload += struct.pack(">H", len(client_ids))
        for cid in client_ids:
            payload += _len16(cid.encode())
        peers = self.registry.endpoints(SERVICE)
        outs = await asyncio.gather(
            *(self._call_peer(ep, "exist", bytes(payload)) for ep in peers),
            return_exceptions=True)
        for out in outs:
            if isinstance(out, BaseException):
                continue
            for i, b in enumerate(out[:len(alive)]):
                alive[i] = alive[i] or bool(b)
        return alive
