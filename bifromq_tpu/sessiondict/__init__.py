from .service import SessionDictClient, SessionDictRPCService  # noqa: F401
