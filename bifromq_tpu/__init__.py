"""bifromq_tpu — a TPU-native, multi-tenant MQTT broker framework.

A ground-up rebuild of the capabilities of Apache BifroMQ (reference:
/root/reference, Java) designed TPU-first: the publish→route-match hot path
(per-tenant subscription trie walk, reference
bifromq-dist/bifromq-dist-worker/.../cache/TenantRouteMatcher.java:68) is
compiled to a flat level-packed trie automaton resident in device HBM and
matched with vmap'd JAX walks, tenant-sharded across a `jax.sharding.Mesh`.

Package layout
--------------
- ``utils``    — topic machinery, HLC, codecs (≈ bifromq-util / base-hlc)
- ``types``    — shared value types (≈ bifromq-common-type protos)
- ``models``   — the match-plane "models": trie automaton compiler, oracle
                 matcher, retained-topic index
- ``ops``      — JAX/pallas kernels: trie-walk NFA, compaction, fan-out count
- ``parallel`` — device mesh, tenant sharding, replicated/sharded match step
"""

__version__ = "0.1.0"
