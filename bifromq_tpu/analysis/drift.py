"""R5 — trace-span and metric-registry drift.

Observability names are stringly-typed: a typo'd span or stage name
silently creates a new series nobody dashboards, and a README table row
for a deleted span misleads the operator reading a live trace. Checks:

- **R5/span-doc**: every span name opened in code (``trace.span(...)``,
  ``trace.record_finished(...)``) must appear in the README (backticked
  anywhere); every row of the README "Span taxonomy" table must still be
  opened somewhere in code.
- **R5/stage**: every literal stage fed to the always-on stage
  histograms (``STAGES.record``, ``Batcher(stage=...)``,
  ``OBS.record_latency``) must be in ``utils.metrics.KNOWN_STAGES``, and
  every registered stage must be emitted somewhere (dead registry
  entries fail too).
- **R5/cache-field**: literal fields passed to ``MATCH_CACHE.inc`` must
  be declared in ``MatchCacheMetrics._FIELDS``.

Both registries are parsed from the analyzed tree's
``utils/metrics.py``; when the root has none (fixture runs), the
installed package's registry is used so fixture snippets still check.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Context, Finding, ParsedFile, Rule, dotted_name

_SPAN_OPENERS = {"span", "record_finished"}
_SPAN_NAME_RE = re.compile(r"^[a-z_]+(\.[a-z_]+)+$")
_BACKTICK_RE = re.compile(r"`([^`]+)`")


def _collect_spans(ctx: Context) -> Dict[str, List[Tuple[str, int, str]]]:
    spans: Dict[str, List[Tuple[str, int, str]]] = {}
    for pf in ctx.files:
        for node in ast.walk(pf.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            callee = dotted_name(node.func).rsplit(".", 1)[-1]
            if callee not in _SPAN_OPENERS:
                continue
            a0 = node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, str) \
                    and _SPAN_NAME_RE.match(a0.value):
                spans.setdefault(a0.value, []).append(
                    (pf.path, node.lineno, pf.scope_of(node)))
    return spans


def _readme_span_table(readme: str) -> Set[str]:
    """Span names from the first cell of every row of the table whose
    header starts ``| span |``."""
    out: Set[str] = set()
    in_table = False
    for line in readme.splitlines():
        stripped = line.strip()
        if stripped.startswith("| span |"):
            in_table = True
            continue
        if in_table:
            if not stripped.startswith("|"):
                in_table = False
                continue
            first_cell = stripped.split("|")[1]
            for name in _BACKTICK_RE.findall(first_cell):
                if _SPAN_NAME_RE.match(name):
                    out.add(name)
    return out


def _parse_registries(pf: Optional[ParsedFile]) -> Tuple[Set[str],
                                                         Set[str]]:
    """(KNOWN_STAGES, MatchCacheMetrics._FIELDS) from a metrics module's
    AST; falls back to the installed package when the analyzed root has
    no utils/metrics.py."""
    if pf is None:
        from ..utils.metrics import KNOWN_STAGES, MatchCacheMetrics
        return set(KNOWN_STAGES), set(MatchCacheMetrics._FIELDS)
    stages: Set[str] = set()
    fields: Set[str] = set()

    def str_elts(node: ast.AST) -> Set[str]:
        vals: Set[str] = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                vals.add(n.value)
        return vals

    for node in ast.walk(pf.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and t.id == "KNOWN_STAGES":
                stages = str_elts(node.value)
            elif isinstance(t, ast.Name) and t.id == "_FIELDS":
                fields = str_elts(node.value)
    return stages, fields


class RegistryDriftRule(Rule):
    rule_id = "R5"
    title = "trace/metric registry drift"

    def run(self, ctx: Context) -> List[Finding]:
        out: List[Finding] = []
        metrics_pf = None
        for pf in ctx.files:
            if pf.path.replace("\\", "/").endswith("utils/metrics.py"):
                metrics_pf = pf
                break
        known_stages, cache_fields = _parse_registries(metrics_pf)
        spans = _collect_spans(ctx)

        # -- span <-> README ------------------------------------------------
        if ctx.readme_text is not None:
            # substring check, not backtick pairing: README code fences
            # make global backtick pairing ambiguous
            for name, sites in sorted(spans.items()):
                if name not in ctx.readme_text:
                    path, line, scope = sites[0]
                    out.append(Finding(
                        rule=self.rule_id, path=path, line=line,
                        scope=scope, symbol=name,
                        message=(f"span `{name}` is opened in code but "
                                 f"not documented in README")))
            for name in sorted(_readme_span_table(ctx.readme_text)):
                if name not in spans:
                    out.append(Finding(
                        rule=self.rule_id, path="README.md", line=0,
                        scope="<span-table>", symbol=name,
                        message=(f"README span-taxonomy row `{name}` is "
                                 f"opened nowhere in code — stale doc")))

        # -- stage registry --------------------------------------------------
        emitted: Dict[str, List[Tuple[str, int, str]]] = {}
        for pf in ctx.files:
            for node in ast.walk(pf.tree):
                if not isinstance(node, ast.Call):
                    continue
                stage = self._stage_literal(node)
                if stage is not None:
                    emitted.setdefault(stage, []).append(
                        (pf.path, node.lineno, pf.scope_of(node)))
                self._check_cache_field(pf, node, cache_fields, out)
        if known_stages:
            for stage, sites in sorted(emitted.items()):
                if stage not in known_stages:
                    path, line, scope = sites[0]
                    out.append(Finding(
                        rule=self.rule_id, path=path, line=line,
                        scope=scope, symbol=stage,
                        message=(f"stage `{stage}` recorded but not in "
                                 f"utils.metrics.KNOWN_STAGES — typo'd "
                                 f"stage names create silent orphan "
                                 f"histograms")))
            if metrics_pf is not None:
                for stage in sorted(known_stages - set(emitted)):
                    out.append(Finding(
                        rule=self.rule_id, path=metrics_pf.path, line=0,
                        scope="<KNOWN_STAGES>", symbol=stage,
                        message=(f"KNOWN_STAGES entry `{stage}` is "
                                 f"emitted nowhere — dead registry "
                                 f"entry")))
        return out

    @staticmethod
    def _stage_literal(node: ast.Call) -> Optional[str]:
        callee = dotted_name(node.func)
        short = callee.rsplit(".", 1)[-1]
        # STAGES.record("stage", secs) / STAGES.hist("stage")
        if short in ("record", "hist") and "STAGES" in callee \
                and node.args:
            a0 = node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                return a0.value
        # OBS.record_latency(tenant, "stage", secs)
        if short == "record_latency" and len(node.args) >= 2:
            a1 = node.args[1]
            if isinstance(a1, ast.Constant) and isinstance(a1.value, str):
                return a1.value
        # Batcher(..., stage="x") / BatchCallScheduler(..., stage="x")
        if short in ("Batcher", "BatchCallScheduler"):
            for kw in node.keywords:
                if kw.arg == "stage" \
                        and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    return kw.value.value
        return None

    def _check_cache_field(self, pf: ParsedFile, node: ast.Call,
                           fields: Set[str], out: List[Finding]) -> None:
        callee = dotted_name(node.func)
        if not (callee.endswith(".inc") and "MATCH_CACHE" in callee
                and len(node.args) >= 2):
            return
        a1 = node.args[1]
        if isinstance(a1, ast.Constant) and isinstance(a1.value, str) \
                and fields and a1.value not in fields:
            out.append(Finding(
                rule=self.rule_id, path=pf.path, line=node.lineno,
                scope=pf.scope_of(node), symbol=a1.value,
                message=(f"MATCH_CACHE field `{a1.value}` not declared "
                         f"in MatchCacheMetrics._FIELDS")))
