"""R1 — hot-path host-sync detector.

The device serving path is fast exactly as long as nothing on it forces
a host round-trip: one stray ``.item()`` / ``np.asarray`` /
``block_until_ready`` inside a jit'd walk body (a tracer leak) or the
async dispatch/fetch legs (a hidden synchronize) silently serializes the
dispatch ring and the whole pipeline degrades to the PR-6-era blocking
path. This rule walks every *hot zone* — functions decorated with (or
wrapped by) ``jax.jit`` anywhere in the package, plus the configured
dispatch/fetch-leg scopes in the four hot-path modules — and flags the
known host-sync shapes. Designated sync points (``_fetch_walk`` is THE
readback) carry suppression entries; everything else is a bug.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from .core import (Context, Finding, ParsedFile, Rule, dotted_name,
                   walk_local)

# scopes that are hot by construction even though nothing decorates them:
# the async dispatch/fetch legs, the patch-flush device update, and the
# helpers the jit'd walk bodies call into (reachability is configured,
# not inferred — an AST pass has no call graph across jit boundaries)
HOT_SCOPES: Dict[str, Set[str]] = {
    "models/matcher.py": {
        "TpuMatcher._prepare_probes", "TpuMatcher._dispatch_device",
        "TpuMatcher._dispatch_prepared", "TpuMatcher._walk_primary",
        "TpuMatcher._await_ready_sync",
        "TpuMatcher._fetch_walk", "TpuMatcher._expand_walk",
        "TpuMatcher._device_leg_async", "TpuMatcher._flush_patches",
    },
    "models/pipeline.py": {
        "DispatchRing.start_fetch", "DispatchRing.wait_ready",
    },
    "ops/match.py": {
        "_mix_u32", "_edge_lookup", "_bitonic_desc", "_advance",
        "_count_walk", "_route_walk", "_walk_routes_fn",
        "walk_routes_donated", "patch_device_trie", "_patch_device_trie",
        # ISSUE 19 device fan-out: the expansion/bucketing bodies the
        # jit'd expand stage traces, plus its dispatch wrapper — the
        # compact-pair readback lives in _fetch_walk, nothing here may
        # synchronize
        "_expand_pairs", "_bucket_pairs", "expand_routes",
    },
    # ISSUE 11 byte-plane prep: the device hash kernel's math + the
    # upload/dispatch wrappers feeding it
    # (+ ISSUE 17: the retained FILTER-probe twin — same host-structure
    # + device-hash split, wildcard kind lanes post-masked on device)
    "ops/tokenize.py": {"_hash_lanes", "hash_topics_device",
                        "device_tokenize", "device_tokenize_filters"},
    # (+ ISSUE 19: the pallas expansion kernel body + its dispatch
    # wrapper — the device fan-out twin of the fused walk)
    "models/kernels.py": {"_build_fused", "fused_walk_routes",
                          "_build_expand", "pallas_expand"},
    # ISSUE 12: the standby's per-batch device flush runs after every
    # applied delta batch — it must stay a pure dispatch wrapper (the
    # narrow scatters live in ops/match, already covered above)
    # (+ ISSUE 18: the apply loop itself now folds lag/audit telemetry
    # per record — that instrumentation must stay host-array-free too)
    "replication/standby.py": {"WarmStandby._flush_device",
                               "WarmStandby._offer_inner"},
    # ISSUE 18: the migration copy stream runs between serving batches;
    # its per-chunk progress accounting must not synchronize the ring
    "parallel/reshard.py": {"TenantMigration.step"},
    # ISSUE 15: the mesh serving legs — stage-1 prep (shard routing +
    # tokenize + grid upload), the step enqueue, the per-shard patch
    # flush, and the expansion that runs against the in-flight snapshot
    "parallel/sharded.py": {
        "MeshMatcher._prepare_probes", "MeshMatcher._dispatch_prepared",
        "MeshMatcher._flush_patches", "MeshMatcher._expand_walk",
        "make_match_step", "_shard_scatter", "_shard_scatter_donated",
        "_shard_slice_set", "_shard_slice_set_donated",
        # ISSUE 19: the per-shard expand step (shard_map body) that
        # returns pre-bucketed per-peer pair grids over the permute ring
        "make_expand_step",
    },
    # ISSUE 13 retained serving plane: the scan dispatch leg (patch
    # flush + walk enqueue) and the async ring leg must stay sync-free;
    # the one true synchronization lives in RetainedIndex.fetch_scan —
    # the retained twin of the matcher's designated _fetch_walk readback
    "models/retained.py": {"RetainedIndex.dispatch_scan",
                           "RetainedIndex.flush_device"},
    "ops/retained.py": {"retained_walk", "retained_walk_ext",
                        "patch_retained_tables", "_patch_retained"},
    "retained_plane/scan.py": {"RetainedScanPlane._device_serve_async"},
}

# host-sync call shapes (module-qualified callee names)
_SYNC_CALLS = {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "jax.device_get", "device_get",
}
# host-sync method names (attribute calls on anything)
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}


def _jit_wrapped_names(tree: ast.Module) -> Set[str]:
    """Function names that are jit'd: ``@jax.jit`` /
    ``@functools.partial(jax.jit, ...)`` decorations, plus
    ``name = functools.partial(jax.jit, ...)(fn)`` / ``jax.jit(fn)``
    wrappings (the wrapped ``fn`` becomes hot)."""
    hot: Set[str] = set()

    def is_jit_expr(node: ast.AST) -> bool:
        name = dotted_name(node)
        if name in ("jax.jit", "jit"):
            return True
        if isinstance(node, ast.Call) \
                and dotted_name(node.func) in ("functools.partial",
                                               "partial"):
            return any(dotted_name(a) in ("jax.jit", "jit")
                       for a in node.args)
        return False

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(is_jit_expr(d) for d in node.decorator_list):
                hot.add(node.name)
        elif isinstance(node, ast.Call):
            # jax.jit(fn) / functools.partial(jax.jit, ...)(fn)
            target = None
            if dotted_name(node.func) in ("jax.jit", "jit") and node.args:
                target = node.args[0]
            elif isinstance(node.func, ast.Call) \
                    and is_jit_expr(node.func) and node.args:
                target = node.args[0]
            if isinstance(target, ast.Name):
                hot.add(target.id)
    return hot


class HostSyncRule(Rule):
    rule_id = "R1"
    title = "hot-path host sync"

    def run(self, ctx: Context) -> List[Finding]:
        out: List[Finding] = []
        for pf in ctx.files:
            jit_names = _jit_wrapped_names(pf.tree)
            configured = HOT_SCOPES.get(pf.path.replace("\\", "/"), set())
            seen = self._scan(pf, jit_names, configured, out)
            # dead-config validation (same no-rot contract as dead
            # suppressions): a configured hot scope that matches no def
            # in its file means a rename silently dropped coverage
            for entry in sorted(configured - seen):
                out.append(Finding(
                    rule=self.rule_id, path=pf.path, line=0,
                    scope="<config>", symbol=entry,
                    message=(f"HOT_SCOPES entry `{entry}` matches no "
                             f"function in {pf.path} — renamed hot "
                             f"scope silently lost R1 coverage; update "
                             f"the config")))
        return out

    def _scan(self, pf: ParsedFile, jit_names: Set[str],
              configured: Set[str], out: List[Finding]) -> Set[str]:
        seen: Set[str] = set()

        def visit_defs(node: ast.AST, prefix: str, hot: bool) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qual = f"{prefix}.{child.name}" if prefix \
                        else child.name
                    if qual in configured:
                        seen.add(qual)
                    child_hot = (hot or child.name in jit_names
                                 or qual in configured)
                    if child_hot:
                        self._check_body(pf, child, qual, out)
                    # nested defs inherit hotness (a jit body's inner
                    # step()/body() functions are traced too)
                    visit_defs(child, qual, child_hot)
                elif isinstance(child, ast.ClassDef):
                    cls_prefix = f"{prefix}.{child.name}" if prefix \
                        else child.name
                    visit_defs(child, cls_prefix, hot)
                else:
                    visit_defs(child, prefix, hot)

        visit_defs(pf.tree, "", False)
        return seen

    def _check_body(self, pf: ParsedFile, fn: ast.AST, qual: str,
                    out: List[Finding]) -> None:
        # walk_local: visit_defs re-checks nested defs under their own
        # qualname (with inherited hotness) — descending here too would
        # report one site twice under two suppression keys
        for node in walk_local(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            symbol = None
            if callee in _SYNC_CALLS:
                symbol = callee
                msg = (f"host sync `{callee}(...)` in hot zone `{qual}` "
                       f"— forces a device round-trip on the match path")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _SYNC_METHODS \
                    and not node.args and not node.keywords:
                symbol = f".{node.func.attr}"
                msg = (f"host sync `.{node.func.attr}()` in hot zone "
                       f"`{qual}` — blocks until the device result "
                       f"lands on host")
            elif callee in ("float", "int") and len(node.args) == 1:
                if self._scalar_coercion_suspect(node.args[0]):
                    symbol = f"{callee}()"
                    msg = (f"`{callee}(...)` on a (possibly device) "
                           f"array in hot zone `{qual}` — scalar "
                           f"coercion is an implicit blocking fetch")
            if symbol is not None:
                out.append(Finding(
                    rule=self.rule_id, path=pf.path, line=node.lineno,
                    scope=qual, symbol=symbol, message=msg))

    @staticmethod
    def _scalar_coercion_suspect(arg: ast.AST) -> bool:
        """float(x)/int(x) is only suspect when x could be a device
        array: bare names and subscripts qualify; attribute reads of
        host-side shape/size metadata (``a.shape[0]``, ``a.nbytes``)
        and literals do not."""
        if isinstance(arg, ast.Constant):
            return False
        if isinstance(arg, ast.Name):
            return True
        if isinstance(arg, ast.Subscript):
            base = arg.value
            if isinstance(base, ast.Attribute) and base.attr == "shape":
                return False
            return True
        return False
