"""R4 — lock discipline across the threaded modules.

Two sub-checks over the lock-acquisition graph (locks = module-level
``NAME = threading.Lock()`` / ``self.NAME = threading.Lock()`` bindings,
acquisitions = ``with <lock>:`` blocks):

- **R4/order**: inconsistent pairwise lock order — if one code path
  acquires A then B and another B then A, the process can deadlock the
  moment both run concurrently. Nesting is tracked syntactically plus
  one call level (a ``with A:`` body calling a local function that takes
  B counts as A→B).
- **R4/blocking**: a blocking call — device fetch, ``time.sleep``,
  subprocess, file/network I/O, ``Thread.join``, ``Future.result`` — or
  an ``await`` executed while holding a lock. Every waiter on that lock
  (often the publish hot path's metric inc) stalls behind the slow
  operation; the shipped pattern is copy-under-lock, work outside
  (``MetricsRegistry.snapshot``). Deliberately-serialized I/O (the
  segment store, the one-time native build) carries suppressions.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Context, Finding, ParsedFile, Rule, dotted_name

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition",
               "Lock", "RLock", "Condition"}

# callee names that block the calling thread
_BLOCKING_CALLS = {
    "time.sleep", "sleep", "open",
    "os.remove", "os.unlink", "os.rename", "os.replace",
    "subprocess.run", "subprocess.Popen", "subprocess.check_call",
    "subprocess.check_output", "urlopen", "urllib.request.urlopen",
    "socket.create_connection",
    "np.asarray", "np.array", "jax.device_get",
}
_BLOCKING_METHODS = {"result", "block_until_ready", "join_thread",
                     "recv", "sendall", "connect"}


def _lock_binding(node: ast.Assign) -> Optional[str]:
    """'NAME' / 'self.NAME' when this assignment binds a lock ctor."""
    if not (isinstance(node.value, ast.Call)
            and dotted_name(node.value.func) in _LOCK_CTORS
            and len(node.targets) == 1):
        return None
    t = node.targets[0]
    if isinstance(t, ast.Name):
        return t.id
    if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
            and t.value.id == "self":
        return f"self.{t.attr}"
    return None


class LockDisciplineRule(Rule):
    rule_id = "R4"
    title = "lock discipline"

    def run(self, ctx: Context) -> List[Finding]:
        out: List[Finding] = []
        # ordered pairs across the whole tree: (lockA, lockB) -> sites
        pair_sites: Dict[Tuple[str, str], List[Tuple[str, int, str]]] = {}
        for pf in ctx.files:
            self._scan_file(pf, pair_sites, out)
        # inconsistent pairwise order
        for (a, b), sites in sorted(pair_sites.items()):
            if a < b and (b, a) in pair_sites:
                rev = pair_sites[(b, a)]
                for path, line, scope in sites + rev:
                    out.append(Finding(
                        rule=self.rule_id, path=path, line=line,
                        scope=scope, symbol=f"{a}<>{b}",
                        message=(f"inconsistent lock order: `{a}` and "
                                 f"`{b}` are acquired in both orders "
                                 f"across the codebase — deadlock when "
                                 f"the paths run concurrently")))
        return out

    def _scan_file(self, pf: ParsedFile, pair_sites, out) -> None:
        locks = self._collect_locks(pf)
        if not locks:
            return
        # per-function summaries for the one-level call expansion
        fns = self._functions(pf)
        summaries: Dict[str, dict] = {}
        for qual, fn in fns.items():
            summaries[qual] = self._summarize(pf, fn, locks)
        for qual, fn in fns.items():
            self._walk_with_stack(pf, fn, qual, locks, summaries,
                                  pair_sites, out)

    @staticmethod
    def _collect_locks(pf: ParsedFile) -> Dict[str, str]:
        """binding -> lock id (module-qualified, class-scoped for
        ``self.*`` so two classes' ``self._lock`` stay distinct)."""
        locks: Dict[str, str] = {}
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Assign):
                b = _lock_binding(node)
                if b is None:
                    continue
                scope = pf.scope_of(node)
                cls = scope.split(".")[0] if scope else ""
                if b.startswith("self."):
                    locks[f"{cls}|{b}"] = f"{pf.path}::{cls}.{b[5:]}"
                else:
                    locks[f"|{b}"] = f"{pf.path}::{b}"
        return locks

    @staticmethod
    def _functions(pf: ParsedFile):
        out = {}
        for node in ast.walk(pf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out[pf.scope_of(node) or node.name] = node
        return out

    @staticmethod
    def _lock_id(locks: Dict[str, str], expr: ast.AST,
                 scope: str) -> Optional[str]:
        cls = scope.split(".")[0] if scope else ""
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            return locks.get(f"{cls}|self.{expr.attr}")
        if isinstance(expr, ast.Name):
            return locks.get(f"|{expr.id}")
        return None

    def _summarize(self, pf: ParsedFile, fn: ast.AST,
                   locks: Dict[str, str]) -> dict:
        """Direct facts about one function: locks it acquires anywhere,
        and whether it makes a blocking call outside any with-lock (the
        caller-holds-a-lock case the one-level expansion flags)."""
        qual = pf.scope_of(fn) or getattr(fn, "name", "")
        acquired: Set[str] = set()
        blocking: List[Tuple[int, str]] = []
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lid = self._lock_id(locks, item.context_expr, qual)
                    if lid:
                        acquired.add(lid)
            sym = self._blocking_symbol(node)
            if sym:
                blocking.append((node.lineno, sym))
        return {"acquires": acquired, "blocking": blocking}

    @staticmethod
    def _blocking_symbol(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Await):
            return "await"
        if not isinstance(node, ast.Call):
            return None
        callee = dotted_name(node.func)
        if callee in _BLOCKING_CALLS:
            return callee
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _BLOCKING_METHODS:
            return f".{node.func.attr}"
        # Thread.join: `.join()` with no args on a non-str receiver is
        # ambiguous ("sep".join(...) takes an arg, thread.join() doesn't)
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "join" and not node.args \
                and not isinstance(node.func.value, ast.Constant):
            return ".join"
        return None

    def _walk_with_stack(self, pf: ParsedFile, fn: ast.AST, qual: str,
                         locks, summaries, pair_sites, out) -> None:
        def visit(node: ast.AST, held: List[str]) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new_held = list(held)
                for item in node.items:
                    lid = self._lock_id(locks, item.context_expr, qual)
                    if lid:
                        for h in new_held:
                            if h != lid:
                                pair_sites.setdefault(
                                    (h, lid), []).append(
                                    (pf.path, node.lineno, qual))
                        new_held.append(lid)
                    else:
                        # a non-lock context expression can itself
                        # block (`with open(...)`); items evaluate left
                        # to right, so locks acquired by EARLIER items
                        # of this same statement are already held —
                        # `with self._lock, open(p):` opens under the
                        # lock
                        visit(item.context_expr, new_held)
                for child in node.body:
                    visit(child, new_held)
                return
            if held:
                sym = self._blocking_symbol(node)
                if sym:
                    out.append(Finding(
                        rule=self.rule_id, path=pf.path,
                        line=node.lineno, scope=qual, symbol=sym,
                        message=(f"blocking call `{sym}` while holding "
                                 f"`{held[-1].split('::')[-1]}` — every "
                                 f"waiter on the lock stalls behind it; "
                                 f"copy under the lock, do the slow "
                                 f"work outside")))
                # one-level call expansion: local callee that itself
                # acquires a lock (order pair) or blocks
                if isinstance(node, ast.Call):
                    callee = dotted_name(node.func)
                    short = callee.replace("self.", "")
                    target = None
                    cls = qual.split(".")[0] if "." in qual else ""
                    for cand in (f"{cls}.{short}", short):
                        if cand in summaries:
                            target = cand
                            break
                    if target is not None and target != qual:
                        for lid in summaries[target]["acquires"]:
                            for h in held:
                                if h != lid:
                                    pair_sites.setdefault(
                                        (h, lid), []).append(
                                        (pf.path, node.lineno, qual))
                        for bl, bsym in summaries[target]["blocking"]:
                            out.append(Finding(
                                rule=self.rule_id, path=pf.path,
                                line=node.lineno, scope=qual,
                                symbol=f"{short}->{bsym}",
                                message=(f"`{short}()` blocks "
                                         f"(`{bsym}` at line {bl}) and "
                                         f"is called while holding "
                                         f"`{held[-1].split('::')[-1]}`"
                                         )))
            # don't descend into nested defs — they run later, not
            # under this lock
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                visit(child, held)

        for stmt in fn.body:
            visit(stmt, [])
