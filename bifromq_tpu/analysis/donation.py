"""R2 — use-after-donate dataflow.

``donate_argnums`` hands a buffer's device memory to XLA: the Python
object survives, but touching its device buffer after the call raises
"Array has been deleted" — or worse, on backends that alias eagerly,
reads garbage mid-overwrite. PRs 6–7 each shipped a hand-audited fix for
this class (the dispatch ring's quarantine exists because of it). This
rule finds every donating callee — jit wrappers declared with
``donate_argnums`` in the analyzed tree, plus the known serving wrappers
— and walks each calling function linearly: a read of a donated binding
after the donation, with no intervening reassignment or quarantine
hand-off, is an error.

Aliases are followed one hop (``fn = walk_routes_donated if donate else
walk_routes`` marks ``fn`` donating — conservative: the donated branch
is assumed reachable).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from .core import (Context, Finding, ParsedFile, Rule, dotted_name,
                   walk_local)

# serving wrappers whose donation is declared in another module (the
# AST pass sees one file at a time): callee name -> donated arg indices
KNOWN_DONATING = {
    "walk_routes_donated": (1,),
    "_walk_routes_donated_jit": (1,),
    # conditional: only donates when called with donate=<not False> —
    # the rule special-cases the kwarg before trusting this index
    "patch_device_trie": (0,),
}

# receivers whose .add()/.reclaim() park a possibly-donated buffer until
# the device is done with it — the sanctioned post-donation hand-off
_QUARANTINE_METHODS = {"add", "reclaim"}


def _donating_defs(tree: ast.Module) -> Dict[str, Tuple[int, ...]]:
    """Names bound to a jit with ``donate_argnums`` in this module:
    ``@functools.partial(jax.jit, donate_argnums=...)`` decorations and
    ``name = functools.partial(jax.jit, donate_argnums=...)(fn)``."""
    out: Dict[str, Tuple[int, ...]] = dict(KNOWN_DONATING)

    def donated_indices(call: ast.Call) -> Tuple[int, ...]:
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                v = kw.value
                if isinstance(v, ast.Constant) \
                        and isinstance(v.value, int):
                    return (v.value,)
                if isinstance(v, (ast.Tuple, ast.List)):
                    return tuple(e.value for e in v.elts
                                 if isinstance(e, ast.Constant))
        return ()

    def is_jit_partial(node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and dotted_name(node.func) in ("functools.partial",
                                               "partial")
                and any(dotted_name(a) in ("jax.jit", "jit")
                        for a in node.args))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if is_jit_partial(dec):
                    idx = donated_indices(dec)
                    if idx:
                        out[node.name] = idx
    # second pass for `name = partial(jax.jit, donate_argnums=...)(fn)`
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Call)
                and is_jit_partial(node.value.func)):
            continue
        idx = donated_indices(node.value.func)
        if not idx:
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                out[tgt.id] = idx
    return out


def _binding_repr(node: ast.AST) -> str:
    """A trackable binding: a bare name or a ``self.attr`` read."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return f"self.{node.attr}"
    return ""


class UseAfterDonateRule(Rule):
    rule_id = "R2"
    title = "use-after-donate"

    def run(self, ctx: Context) -> List[Finding]:
        out: List[Finding] = []
        defined: set = set()
        for pf in ctx.files:
            donating = _donating_defs(pf.tree)
            for node in ast.walk(pf.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    defined.add(node.name)
                    self._check_fn(pf, node, donating, out)
                elif isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            defined.add(t.id)
        # dead-config validation (same no-rot contract as dead
        # suppressions), gated to trees that actually contain the
        # module the wrappers live in — fixture runs skip it
        if any(pf.path.replace("\\", "/").endswith("ops/match.py")
               for pf in ctx.files):
            for name in sorted(set(KNOWN_DONATING) - defined):
                out.append(Finding(
                    rule=self.rule_id, path="ops/match.py", line=0,
                    scope="<config>", symbol=name,
                    message=(f"KNOWN_DONATING entry `{name}` is "
                             f"defined nowhere in the analyzed tree — "
                             f"renamed donating wrapper silently lost "
                             f"R2 coverage; update the config")))
        return out

    def _check_fn(self, pf: ParsedFile, fn: ast.AST,
                  donating: Dict[str, Tuple[int, ...]],
                  out: List[Finding]) -> None:
        local = dict(donating)
        # one-hop alias: x = donating_callee / x = a if c else b
        # (walk_local: a nested def's statements belong to ITS scope —
        # the per-FunctionDef driver analyzes it separately)
        for node in walk_local(fn):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            v = node.value
            cands = [v.body, v.orelse] if isinstance(v, ast.IfExp) else [v]
            for c in cands:
                name = dotted_name(c)
                if name in local:
                    local[node.targets[0].id] = local[name]
        # linear scan: donation events then later reads, by line order
        events: List[Tuple[int, str, ast.Call]] = []
        for node in walk_local(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            # strip module prefixes: ops.match.walk_routes_donated etc.
            short = callee.rsplit(".", 1)[-1]
            idx = local.get(callee) or local.get(short)
            if not idx:
                continue
            # `patch_device_trie(dev, ..., donate=False)` is functional —
            # only a donate kwarg that is not literally False donates
            if short == "patch_device_trie":
                dkw = next((kw.value for kw in node.keywords
                            if kw.arg == "donate"), None)
                if dkw is None or (isinstance(dkw, ast.Constant)
                                   and dkw.value is False):
                    continue
                idx = (0,)
            for i in idx:
                if i < len(node.args):
                    b = _binding_repr(node.args[i])
                    if b:
                        events.append((node.lineno, b, node))
        if not events:
            return
        qual = pf.scope_of(fn)
        for don_line, binding, call in events:
            self._check_reads_after(pf, fn, qual, don_line, binding, out)

    def _check_reads_after(self, pf: ParsedFile, fn: ast.AST, qual: str,
                           don_line: int, binding: str,
                           out: List[Finding]) -> None:
        # find the first reassignment after the donation; reads between
        # donation and reassignment are the violation window. A
        # reassignment ON the donation line (`x = f(x)`) closes the
        # window immediately.
        reassign_line = None
        for node in walk_local(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if _binding_repr(t) == binding \
                            and node.lineno >= don_line:
                        if reassign_line is None \
                                or node.lineno < reassign_line:
                            reassign_line = node.lineno
        for node in walk_local(fn):
            if not (isinstance(node, (ast.Name, ast.Attribute))
                    and isinstance(getattr(node, "ctx", None), ast.Load)
                    and _binding_repr(node) == binding):
                continue
            line = node.lineno
            if line <= don_line:
                continue
            if reassign_line is not None and line >= reassign_line:
                continue
            if self._is_quarantine_handoff(fn, node):
                continue
            out.append(Finding(
                rule=self.rule_id, path=pf.path, line=line,
                scope=qual, symbol=binding,
                message=(f"`{binding}` read after being donated at line "
                         f"{don_line} — donated buffers may already be "
                         f"freed/aliased by XLA; re-read the host copy, "
                         f"reassign, or quarantine")))

    @staticmethod
    def _is_quarantine_handoff(fn: ast.AST, read: ast.AST) -> bool:
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _QUARANTINE_METHODS
                    and any(a is read for a in node.args)):
                recv = dotted_name(node.func.value).lower()
                if "quarantine" in recv or "ring" in recv:
                    return True
        return False
