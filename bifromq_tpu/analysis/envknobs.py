"""R3 — env-knob discipline.

Three sub-checks, all born from shipped review fixes (PR 7: SHEDDER /
INGEST_GATE knobs frozen at module import while every sibling resolved
lazily):

- **R3/direct**: a ``BIFROMQ_*`` knob read through raw ``os.environ``
  (``.get``, subscript, ``in``, ``os.getenv``) anywhere outside
  ``utils/env.py`` — every knob must go through the lazy helpers so
  parse-fallback behavior cannot fork per call site.
- **R3/import-time**: any knob resolution (helper call included) at
  module scope — the value freezes before the embedding broker or a
  monkeypatching test can set its env.
- **R3/readme**: drift between the knob set referenced in code and the
  README knob documentation, both directions (an undocumented knob is
  unusable; a documented-but-deleted knob is a trap). Skipped when the
  context has no README (fixture runs).
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from .core import (Context, Finding, ParsedFile, Rule, dotted_name,
                   str_literal_prefix)

_ENV_HELPERS = {"env_float", "env_int", "env_str", "env_bool",
                "env_opt_str", "env_opt_float"}
_KNOB_RE = re.compile(r"^BIFROMQ_[A-Z0-9_]+$")
_README_KNOB_RE = re.compile(r"BIFROMQ_[A-Z0-9_]+")


def _knob_of(node: ast.AST) -> Optional[str]:
    """The BIFROMQ knob named by a literal (or f-string prefix)."""
    s = str_literal_prefix(node)
    if s is None or not s.startswith("BIFROMQ_"):
        return None
    if isinstance(node, ast.JoinedStr):
        return s + "*"      # dynamic suffix (f-string): report the prefix
    return s if _KNOB_RE.match(s) else None


def _environ_read_knob(node: ast.AST) -> Optional[str]:
    """BIFROMQ knob read through raw os.environ / os.getenv, or None."""
    if isinstance(node, ast.Call):
        callee = dotted_name(node.func)
        if callee in ("os.environ.get", "environ.get", "os.getenv") \
                and node.args:
            return _knob_of(node.args[0])
    if isinstance(node, ast.Subscript) \
            and isinstance(getattr(node, "ctx", None), ast.Load) \
            and dotted_name(node.value) in ("os.environ", "environ"):
        return _knob_of(node.slice)
    if isinstance(node, ast.Compare) and len(node.ops) == 1 \
            and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
            and dotted_name(node.comparators[0]) in ("os.environ",
                                                     "environ"):
        return _knob_of(node.left)
    return None


class EnvKnobRule(Rule):
    rule_id = "R3"
    title = "env-knob discipline"

    @staticmethod
    def _import_time_index(pf: ParsedFile) -> tuple:
        """(function line spans, ids of default-argument expression
        nodes). Code OUTSIDE every def span executes at import (module
        scope AND class bodies) — and so do def default expressions,
        even though their lines sit INSIDE the def's span (the PR 7
        frozen-knob class wearing a default argument)."""
        spans = []
        default_ids = set()
        for node in ast.walk(pf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                hi = max((getattr(n, "lineno", node.lineno)
                          for n in ast.walk(node)), default=node.lineno)
                spans.append((node.lineno, hi))
                for d in (list(node.args.defaults)
                          + [k for k in node.args.kw_defaults
                             if k is not None]):
                    for sub in ast.walk(d):
                        default_ids.add(id(sub))
        return spans, default_ids

    def run(self, ctx: Context) -> List[Finding]:
        out: List[Finding] = []
        code_knobs: Set[str] = set()
        for pf in ctx.files:
            exempt = pf.path.replace("\\", "/").endswith("utils/env.py")
            fn_spans, default_ids = self._import_time_index(pf)

            def at_import_time(node) -> bool:
                if id(node) in default_ids:
                    return True
                line = getattr(node, "lineno", 0)
                return not any(lo <= line <= hi for lo, hi in fn_spans)

            for node in ast.walk(pf.tree):
                # collect every knob literal for the README drift check
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str) \
                        and _KNOB_RE.match(node.value):
                    code_knobs.add(node.value)
                knob = _environ_read_knob(node)
                if knob is not None and not exempt:
                    out.append(Finding(
                        rule=self.rule_id, path=pf.path,
                        line=node.lineno, scope=pf.scope_of(node),
                        symbol=knob,
                        message=(f"raw os.environ read of `{knob}` — "
                                 f"route BIFROMQ_* knobs through the "
                                 f"utils/env.py lazy helpers")))
                # import-time resolution: helper call outside every def
                # — module scope OR a class body, both run at import
                # (the PR 7 frozen-knob class)
                if isinstance(node, ast.Call):
                    callee = dotted_name(node.func).rsplit(".", 1)[-1]
                    if callee in _ENV_HELPERS and node.args:
                        k = _knob_of(node.args[0])
                        if k is not None and at_import_time(node):
                            out.append(Finding(
                                rule=self.rule_id, path=pf.path,
                                line=node.lineno,
                                scope=pf.scope_of(node),
                                symbol=k,
                                message=(f"`{k}` resolved at import "
                                         f"time — the value freezes "
                                         f"before the embedder can set "
                                         f"its env; resolve lazily at "
                                         f"first use")))
            # sysprops-style dynamic knobs: enum tuples whose first
            # element is the env suffix — register the full name so the
            # README drift check covers them
            if pf.path.replace("\\", "/").endswith("utils/sysprops.py"):
                code_knobs.update(self._sysprops_knobs(pf))
        out.extend(self._readme_drift(ctx, code_knobs))
        return out

    @staticmethod
    def _sysprops_knobs(pf: ParsedFile) -> Set[str]:
        knobs: Set[str] = set()
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Tuple) \
                    and node.value.elts \
                    and isinstance(node.value.elts[0], ast.Constant) \
                    and isinstance(node.value.elts[0].value, str):
                suffix = node.value.elts[0].value
                if re.match(r"^[A-Z0-9_]+$", suffix):
                    knobs.add(f"BIFROMQ_{suffix}")
        return knobs

    def _readme_drift(self, ctx: Context,
                      code_knobs: Set[str]) -> List[Finding]:
        if ctx.readme_text is None:
            return []
        readme_knobs = set(_README_KNOB_RE.findall(ctx.readme_text))
        out: List[Finding] = []
        for knob in sorted(code_knobs - readme_knobs):
            out.append(Finding(
                rule=self.rule_id, path="README.md", line=0,
                scope="<knobs>", symbol=knob,
                message=(f"`{knob}` is read by code but absent from the "
                         f"README knob documentation")))
        for knob in sorted(readme_knobs - code_knobs):
            out.append(Finding(
                rule=self.rule_id, path="README.md", line=0,
                scope="<knobs>", symbol=knob,
                message=(f"`{knob}` is documented in README but no code "
                         f"reads it — dead doc or renamed knob")))
        return out
