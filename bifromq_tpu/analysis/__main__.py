"""CLI: ``python -m bifromq_tpu.analysis [--root DIR] [--json]
[--write-stamp]``.

Exit codes: 0 clean; 1 unsuppressed findings or dead suppressions;
2 bad invocation / malformed suppression file.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import (SUPPRESSIONS_PATH, SuppressionError, run_analysis,
               write_stamp)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m bifromq_tpu.analysis")
    p.add_argument("--root", default=None,
                   help="package dir to analyze (default: the installed "
                        "bifromq_tpu)")
    p.add_argument("--readme", default=None,
                   help="README for the drift checks (default: the "
                        "repo's when analyzing the installed package)")
    p.add_argument("--suppressions", default=None,
                   help=f"suppression file (default: {SUPPRESSIONS_PATH}"
                        f" for the installed package; none for --root)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report")
    p.add_argument("--write-stamp", action="store_true",
                   help="refresh the checked-in stamp.json on a clean run")
    args = p.parse_args(argv)
    try:
        report = run_analysis(root=args.root, readme=args.readme,
                              suppressions=args.suppressions)
    except SuppressionError as e:
        print(f"graftcheck: {e}", file=sys.stderr)
        return 2
    if args.json:
        payload = report.to_dict()
        payload["findings"] = [f.render() for f in report.findings]
        payload["dead"] = [s.key for s in report.dead_suppressions]
        print(json.dumps(payload, indent=1))
    else:
        for f in report.findings:
            print(f.render())
        for s in report.dead_suppressions:
            print(f"suppressions.txt:{s.lineno}: dead suppression "
                  f"(matches no finding): {s.key}")
        d = report.to_dict()
        print(f"graftcheck: {d['rules']} rules, "
              f"{d['suppressed']} suppressed "
              f"({d['suppressions']} entries), "
              f"{d['unsuppressed']} unsuppressed, "
              f"{d['dead_suppressions']} dead suppressions "
              f"[hash {d['hash']}]")
    if report.clean and args.write_stamp:
        if args.root or args.suppressions or args.readme:
            # the checked-in stamp describes THE package against ITS
            # suppression file — a clean run over some other tree must
            # never overwrite it (GET /metrics serves this file)
            print("graftcheck: --write-stamp only applies to the "
                  "default (installed-package) analysis; drop --root/"
                  "--suppressions/--readme", file=sys.stderr)
            return 2
        write_stamp(report)
        print(f"stamp written: {report.stamp_hash()}")
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main())
