"""Transfer-guard sanitizer (ISSUE 10 — the dynamic half of graftcheck).

The AST rules catch the host-sync shapes they can *name*; anything else
— a numpy array slipping into a jit'd walk as an implicit host-to-device
upload, a library call that synchronizes under the hood — needs the
runtime to object. ``jax.transfer_guard("disallow")`` does exactly that:
implicit transfers raise, while the hot path's *declared* transfers
(``jax.device_put`` on probe upload, the ``_fetch_walk`` readback) stay
legal because they are explicit.

Usage (tests/test_sanitize.py drives sync, async and patched-churn
match paths through this):

    warm_up_the_path()                  # compiles happen unguarded
    with sanitize.no_implicit_transfers():
        serve_the_path()                # any stray transfer raises

``assert_guard_arms()`` first proves the guard actually fires on the
running jax version — a silently-vacuous sanitizer is worse than none.
"""

from __future__ import annotations

import contextlib


class TransferGuardUnavailable(RuntimeError):
    """The running jax cannot enforce the transfer guard — the
    sanitizer tests must FAIL (not skip silently): a green run that
    guarded nothing is the worst outcome."""


def assert_guard_arms() -> None:
    """Prove ``transfer_guard('disallow')`` rejects an implicit
    host-to-device transfer on this backend/version."""
    import jax
    import numpy as np
    if not hasattr(jax, "transfer_guard"):
        raise TransferGuardUnavailable(
            "jax.transfer_guard missing on this jax version")
    fn = jax.jit(lambda a: a + 1)
    probe = np.arange(2, dtype=np.int32)
    fn(jax.device_put(probe))           # compile outside the guard
    tripped = False
    with jax.transfer_guard("disallow"):
        try:
            fn(probe)                   # implicit h2d — must raise
        except Exception:  # noqa: BLE001 — any rejection arms us
            tripped = True
    if not tripped:
        raise TransferGuardUnavailable(
            "transfer_guard('disallow') did not reject an implicit "
            "host-to-device transfer — the sanitizer would be vacuous")


@contextlib.contextmanager
def no_implicit_transfers():
    """Run the enclosed block with implicit device transfers disallowed.

    Explicit ``jax.device_put`` / ``jax.device_get`` stay legal — the
    discipline this enforces is "every transfer on the hot path is a
    *decision*, visible at a named call site", which is also exactly
    what the R1 suppression file documents.
    """
    import jax
    with jax.transfer_guard("disallow"):
        yield
