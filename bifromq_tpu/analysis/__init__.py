"""graftcheck — project-specific static analysis for the device hot path
(ISSUE 10 tentpole).

Five rules, each a mechanically-detectable bug class a prior PR shipped
a hand-found fix for:

====  =====================================================
R1    hot-path host sync (``.item()`` / ``np.asarray`` /
      ``block_until_ready`` reachable from the jit'd walk
      bodies and the async dispatch/fetch legs)
R2    use-after-donate (reads of a ``donate_argnums`` binding
      after the donating call, no quarantine/reassign between)
R3    env-knob discipline (raw ``os.environ`` BIFROMQ_* reads,
      import-time knob freezing, README knob-table drift)
R4    lock discipline (inconsistent pairwise lock order,
      blocking calls while holding a lock)
R5    trace/metric registry drift (span names vs the README
      span table, stage/metric names vs the registries)
====  =====================================================

Run ``python -m bifromq_tpu.analysis`` over the package; tier-1 runs it
as a zero-findings test (tests/test_analysis.py), tier-2 as
``scripts/analysis_check.sh``. Intentional exceptions live in
``suppressions.txt`` next to this file — every entry needs a
justification and must still match a live finding. ``stamp.json`` is
the checked-in last-run stamp served under ``GET /metrics`` build-info
so analyzer drift is visible on a live node.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

from .core import (Context, Finding, Report, Rule,  # noqa: F401
                   SuppressionError, apply_suppressions,
                   parse_suppressions)
from .donation import UseAfterDonateRule
from .drift import RegistryDriftRule
from .envknobs import EnvKnobRule
from .hostsync import HostSyncRule
from .locks import LockDisciplineRule

ALL_RULES = (HostSyncRule, UseAfterDonateRule, EnvKnobRule,
             LockDisciplineRule, RegistryDriftRule)

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
SUPPRESSIONS_PATH = os.path.join(_PKG_DIR, "suppressions.txt")
STAMP_PATH = os.path.join(_PKG_DIR, "stamp.json")


def default_root() -> str:
    """The installed bifromq_tpu package directory."""
    return os.path.dirname(_PKG_DIR)


def default_readme() -> Optional[str]:
    """README.md next to the package (repo checkout); None when the
    package is installed without one — README-drift checks then skip."""
    cand = os.path.join(os.path.dirname(default_root()), "README.md")
    return cand if os.path.exists(cand) else None


def run_analysis(root: Optional[str] = None,
                 readme: Optional[str] = None,
                 suppressions: Optional[str] = None,
                 rules: Optional[List[type]] = None) -> Report:
    """Run graftcheck and fold in suppressions. Defaults analyze the
    installed package against its own suppression file."""
    if root is None:
        root = default_root()
        if readme is None:
            readme = default_readme()
        if suppressions is None:
            suppressions = SUPPRESSIONS_PATH
    ctx = Context(root, readme=readme)
    findings: List[Finding] = list(ctx.parse_errors)
    rule_ids = []
    for rule_cls in (rules or ALL_RULES):
        rule = rule_cls()
        rule_ids.append(rule.rule_id)
        findings.extend(rule.run(ctx))
    sups = parse_suppressions(suppressions) if suppressions else []
    report = apply_suppressions(findings, sups)
    report.rule_ids = rule_ids
    return report


def write_stamp(report: Report, path: str = STAMP_PATH) -> dict:
    global _STAMP_CACHE
    stamp = report.to_dict()
    with open(path, "w", encoding="utf-8") as f:
        json.dump(stamp, f, indent=1, sort_keys=True)
        f.write("\n")
    _STAMP_CACHE = None     # the process just changed what it serves
    return stamp


def _load_stamp() -> dict:
    # cached: the checked-in stamp is immutable for the process
    # lifetime, and /metrics scrapes must not pay file I/O per hit
    try:
        with open(STAMP_PATH, encoding="utf-8") as f:
            stamp = json.load(f)
        stamp["stamp"] = "ok"
        return stamp
    except (OSError, ValueError):
        return {"stamp": "missing"}


_STAMP_CACHE: Optional[dict] = None


def build_info() -> dict:
    """The ``GET /metrics`` build-info payload: the checked-in stamp
    (rule count, suppression count, last-run hash). Never raises — a
    missing/corrupt stamp reports as such instead of breaking the
    metrics scrape."""
    global _STAMP_CACHE
    if _STAMP_CACHE is None:
        _STAMP_CACHE = _load_stamp()
    return dict(_STAMP_CACHE)
