"""graftcheck core: parsed-file context, findings, suppressions (ISSUE 10).

The analyzer is AST-only — it never imports the code it checks, so a
broken module is a parse finding, not a crash, and the suite can run on
fixture snippets that intentionally violate the rules. Each rule is a
``Rule`` subclass producing :class:`Finding` rows; intentional
exceptions live in a checked-in suppression file keyed by a *site key*
(rule, relative path, enclosing scope, symbol) — stable across line
churn, unlike line numbers — and every entry must still match a live
finding (dead suppressions fail the run, so the file cannot rot).
"""

from __future__ import annotations

import ast
import hashlib
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True)
class Finding:
    rule: str       # stable rule id (R1..R5)
    path: str       # path relative to the analysis root
    line: int
    scope: str      # dotted qualname of the enclosing def(s); '' = module
    symbol: str     # what tripped: call name, knob, lock pair, span name
    message: str

    @property
    def key(self) -> str:
        """The suppression-file site key (line-number-free on purpose)."""
        return f"{self.rule} {self.path} {self.scope or '<module>'} " \
               f"{self.symbol}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] {self.message}"
                f"  (key: {self.key})")


@dataclass
class Suppression:
    rule: str
    path: str
    scope: str
    symbol: str
    justification: str
    lineno: int
    hits: int = 0

    @property
    def key(self) -> str:
        return f"{self.rule} {self.path} {self.scope} {self.symbol}"


class SuppressionError(ValueError):
    pass


def parse_suppressions(path: str) -> List[Suppression]:
    """One entry per line: ``RULE path scope symbol -- justification``.

    ``scope`` is the dotted enclosing-def qualname (``<module>`` for
    module level). The justification is mandatory — a suppression
    without a reason is indistinguishable from a silenced bug.
    """
    out: List[Suppression] = []
    if not os.path.exists(path):
        return out
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if "--" not in line:
                raise SuppressionError(
                    f"{path}:{i}: missing '-- justification'")
            site, justification = line.split("--", 1)
            justification = justification.strip()
            if not justification:
                raise SuppressionError(
                    f"{path}:{i}: empty justification")
            parts = site.split()
            if len(parts) != 4:
                raise SuppressionError(
                    f"{path}:{i}: expected 'RULE path scope symbol', "
                    f"got {len(parts)} fields")
            out.append(Suppression(*parts, justification=justification,
                                   lineno=i))
    return out


@dataclass
class ParsedFile:
    path: str           # relative to root
    abspath: str
    tree: ast.Module
    source: str

    _span_index: Optional[List[Tuple[Tuple[int, int], str]]] = \
        field(default=None, repr=False)

    def scope_of(self, node: ast.AST) -> str:
        """Dotted qualname of the innermost def/class enclosing ``node``
        (by position) — '' for module level."""
        if self._span_index is None:
            self._span_index = []
            self._index(self.tree, "")
        # the index maps a def/class body line span to its qualname; the
        # innermost (tightest-span) match wins
        lineno = getattr(node, "lineno", 0)
        best, best_span = "", None
        for (lo, hi), name in self._span_index:
            if lo <= lineno <= hi and (best_span is None
                                       or (hi - lo) < best_span):
                best, best_span = name, hi - lo
        return best

    def _index(self, node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                name = f"{prefix}.{child.name}" if prefix else child.name
                hi = max((getattr(n, "lineno", child.lineno)
                          for n in ast.walk(child)), default=child.lineno)
                self._span_index.append(((child.lineno, hi), name))
                self._index(child, name)
            else:
                self._index(child, prefix)


class Context:
    """Everything a rule may read: parsed files, the README (optional),
    and the analysis root."""

    def __init__(self, root: str, readme: Optional[str] = None) -> None:
        self.root = os.path.abspath(root)
        self.readme_path = readme
        self.readme_text: Optional[str] = None
        if readme and os.path.exists(readme):
            with open(readme, encoding="utf-8") as f:
                self.readme_text = f.read()
        self.files: List[ParsedFile] = []
        self.parse_errors: List[Finding] = []
        self._load()

    def _load(self) -> None:
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                abspath = os.path.join(dirpath, fn)
                rel = os.path.relpath(abspath, self.root)
                with open(abspath, encoding="utf-8") as f:
                    src = f.read()
                try:
                    tree = ast.parse(src, filename=rel)
                except SyntaxError as e:
                    self.parse_errors.append(Finding(
                        rule="R0", path=rel, line=e.lineno or 0,
                        scope="", symbol="syntax",
                        message=f"unparseable: {e.msg}"))
                    continue
                self.files.append(ParsedFile(path=rel, abspath=abspath,
                                             tree=tree, source=src))

    def file(self, rel: str) -> Optional[ParsedFile]:
        for pf in self.files:
            if pf.path == rel:
                return pf
        return None


class Rule:
    rule_id = "R?"
    title = ""

    def run(self, ctx: Context) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


@dataclass
class Report:
    findings: List[Finding]
    suppressed: List[Tuple[Finding, Suppression]]
    dead_suppressions: List[Suppression]
    rule_ids: List[str]
    n_suppressions: int

    @property
    def clean(self) -> bool:
        return not self.findings and not self.dead_suppressions

    def stamp_hash(self) -> str:
        """Deterministic digest of the run's outcome: rule set, every
        finding key (suppressed or not), and every suppression key —
        two nodes disagreeing on this hash are running different code
        or different suppressions."""
        h = hashlib.sha256()
        for rid in sorted(self.rule_ids):
            h.update(rid.encode())
        for f in sorted(self.findings, key=lambda f: f.key):
            h.update(f.key.encode())
        for f, s in sorted(self.suppressed, key=lambda p: p[0].key):
            h.update(f.key.encode())
            h.update(s.key.encode())
        return h.hexdigest()[:16]

    def to_dict(self) -> dict:
        return {"rules": len(self.rule_ids),
                "rule_ids": sorted(self.rule_ids),
                "suppressions": self.n_suppressions,
                "unsuppressed": len(self.findings),
                "suppressed": len(self.suppressed),
                "dead_suppressions": len(self.dead_suppressions),
                "hash": self.stamp_hash()}


def apply_suppressions(findings: List[Finding],
                       sups: List[Suppression]) -> Report:
    by_key: Dict[str, Suppression] = {s.key: s for s in sups}
    live: List[Finding] = []
    suppressed: List[Tuple[Finding, Suppression]] = []
    for f in findings:
        s = by_key.get(f.key)
        if s is not None:
            s.hits += 1
            suppressed.append((f, s))
        else:
            live.append(f)
    dead = [s for s in sups if s.hits == 0]
    return Report(findings=live, suppressed=suppressed,
                  dead_suppressions=dead, rule_ids=[],
                  n_suppressions=len(sups))


# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------

def walk_local(fn: ast.AST):
    """Walk a function body WITHOUT descending into nested defs/lambdas.

    Rules that analyze one function's linear dataflow (R2) or report
    per-scope sites (R1) must not mix a nested function's statements
    into the enclosing scope: the nested body executes at a different
    time (so e.g. a closure-local reassignment must not close the outer
    donation window), and the per-FunctionDef driver visits nested defs
    separately under their own scope key (walking them twice would
    double-report one site under two suppression keys)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def dotted_name(node: ast.AST) -> str:
    """'np.asarray' for Attribute chains, 'open' for Names, '' otherwise."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


def str_literal_prefix(node: ast.AST) -> Optional[str]:
    """The literal string (or f-string literal prefix) of ``node``."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            return first.value
    return None
