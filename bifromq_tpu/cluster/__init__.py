"""bifromq_tpu.cluster — gossip membership (analog of base-cluster)."""
from .membership import AgentHost

__all__ = ["AgentHost"]
