"""Cluster membership: SWIM-style gossip over UDP (≈ base-cluster).

Reference shape (SURVEY.md §2.2): shared-port UDP gossip transport,
infection-style dissemination (Gossiper.java:46), SWIM direct + indirect
probing (fd/FailureDetector.java:54 probe():190), CRDT-backed member list
with auto-join/heal/drop (HostMemberList, AutoSeeder/AutoHealer/AutoDropper),
and logical *agents* (service groups) riding membership (agent/Agent.java,
IAgentHost.host():65).

Here: one asyncio datagram endpoint per host carries pings/acks with
piggybacked membership + agent state. Member records are (incarnation,
status) LWW registers — a refuting node bumps its own incarnation, the
standard SWIM suspicion-refutation rule. Agents are per-node registrations
disseminated the same way; ``agent_members(agent_id)`` is the service
discovery primitive the RPC layer builds on.
"""

from __future__ import annotations

import asyncio
import json
import logging
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

log = logging.getLogger(__name__)

ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"


@dataclass
class MemberState:
    node_id: str
    addr: Tuple[str, int]
    incarnation: int = 0
    status: str = ALIVE
    # agent_id -> metadata dict (services this node exposes)
    agents: Dict[str, dict] = field(default_factory=dict)
    status_at: float = field(default_factory=time.time)
    tcp_port: int = 0   # large-payload plane (0 = none advertised)

    def record(self) -> dict:
        return {"id": self.node_id, "addr": list(self.addr),
                "inc": self.incarnation, "st": self.status,
                "agents": self.agents, "tcp": self.tcp_port}


class AgentHost(asyncio.DatagramProtocol):
    """One cluster participant (≈ IAgentHost)."""

    PROBE_INTERVAL = 0.15
    PROBE_TIMEOUT = 0.12
    INDIRECT_K = 2
    SUSPECT_TIMEOUT = 0.8
    DEAD_REAP = 5.0
    GOSSIP_FANOUT = 3

    def __init__(self, node_id: str, host: str = "127.0.0.1",
                 port: int = 0, *, seeds: Optional[List[Tuple[str, int]]] = None,
                 rng: Optional[random.Random] = None,
                 tls_server_ctx=None, tls_client_ctx=None,
                 probe_interval_s: Optional[float] = None,
                 probe_timeout_s: Optional[float] = None,
                 suspect_timeout_s: Optional[float] = None,
                 dead_reap_s: Optional[float] = None) -> None:
        self.node_id = node_id
        self.host = host
        self.port = port
        self.seeds = seeds or []
        self.rng = rng or random.Random()
        # failure-detector timing knobs (ISSUE 5): instance overrides of
        # the class defaults. Full broker nodes carry heavier event loops
        # than the in-process test clusters these defaults were tuned on
        # — an operator (or the starter config) can trade detection
        # latency for stability under GC/compile stalls.
        if probe_interval_s is not None:
            self.PROBE_INTERVAL = float(probe_interval_s)
        if probe_timeout_s is not None:
            self.PROBE_TIMEOUT = float(probe_timeout_s)
        if suspect_timeout_s is not None:
            self.SUSPECT_TIMEOUT = float(suspect_timeout_s)
        if dead_reap_s is not None:
            self.DEAD_REAP = float(dead_reap_s)
        self.members: Dict[str, MemberState] = {}
        self.transport: Optional[asyncio.DatagramTransport] = None
        self._probe_task: Optional[asyncio.Task] = None
        self._acks: Dict[int, asyncio.Future] = {}
        # relayed-ping bookkeeping: our seq -> (origin, origin seq, ts);
        # expired in the probe loop (dead targets never ack)
        self._relays: Dict[int, Tuple] = {}
        self._seq = 0
        self._tcp_server: Optional[asyncio.AbstractServer] = None
        # optional TLS for the TCP large-payload plane (the reference's
        # cluster transport supports TLS on both planes; UDP gossip here
        # stays clear like the reference's default — basecluster
        # transport/AbstractTransport.java)
        self._tls_server_ctx = tls_server_ctx
        self._tls_client_ctx = tls_client_ctx
        self._listeners: List[Callable[[], None]] = []
        self._payload_handlers: Dict[str, Callable[[str, dict], None]] = {}
        self.stopped = False

    # ---------------- lifecycle -------------------------------------------

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self.transport, _ = await loop.create_datagram_endpoint(
            lambda: self, local_addr=(self.host, self.port))
        self.port = self.transport.get_extra_info("sockname")[1]
        # large-payload plane: UDP datagrams cap out near 64KB (and
        # fragment badly well before); oversized payloads ride TCP (the
        # reference's dual UDP/TCP cluster transport)
        self._tcp_server = await asyncio.start_server(
            self._on_tcp, self.host, 0, ssl=self._tls_server_ctx)
        tcp_port = self._tcp_server.sockets[0].getsockname()[1]
        self.members[self.node_id] = MemberState(
            node_id=self.node_id, addr=(self.host, self.port),
            tcp_port=tcp_port)
        for seed in self.seeds:
            self._send(tuple(seed), {"t": "join"})
        self._probe_task = loop.create_task(self._probe_loop())

    async def stop(self) -> None:
        self.stopped = True
        if self._probe_task is not None:
            self._probe_task.cancel()
        if self.transport is not None:
            self.transport.close()
        if self._tcp_server is not None:
            self._tcp_server.close()

    # ---------------- payload channel (cluster messenger) -------------------

    def register_payload_handler(self, channel: str,
                                 cb: Callable[[str, dict], None]) -> None:
        """Subscribe to application payloads on ``channel`` (≈ Messenger)."""
        self._payload_handlers[channel] = cb

    UDP_MAX = 60_000    # payloads beyond this ride the TCP plane

    def send_payload(self, node_id: str, channel: str, data: dict) -> bool:
        """Fire-and-forget payload to a member by node id; large payloads
        fall back to the TCP plane (a UDP datagram would be truncated or
        rejected outright)."""
        m = self.members.get(node_id)
        if m is None:
            return False
        msg = {"t": "payload", "ch": channel, "data": data,
               "from": self.node_id, "gossip": []}
        raw = json.dumps(msg).encode()
        if len(raw) > self.UDP_MAX and m.tcp_port:
            asyncio.ensure_future(
                self._send_tcp((m.addr[0], m.tcp_port), raw))
            return True
        self._send(tuple(m.addr), {"t": "payload", "ch": channel,
                                   "data": data})
        return True

    async def _send_tcp(self, addr: Tuple[str, int], raw: bytes) -> None:
        try:
            _r, w = await asyncio.wait_for(
                asyncio.open_connection(*addr, ssl=self._tls_client_ctx),
                2.0)
            w.write(len(raw).to_bytes(4, "big") + raw)
            await w.drain()
            w.close()
        except Exception:  # noqa: BLE001 — fire-and-forget like UDP
            pass

    async def _on_tcp(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            hdr = await reader.readexactly(4)
            n = int.from_bytes(hdr, "big")
            if n > 64 * 1024 * 1024:    # sanity cap
                return
            raw = await reader.readexactly(n)
            self.datagram_received(raw, writer.get_extra_info("peername"))
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    # ---------------- agents (service groups) ------------------------------

    def host_agent(self, agent_id: str, metadata: Optional[dict] = None) -> None:
        """Announce a logical service on this node (≈ agentHost.host(id))."""
        me = self.members[self.node_id]
        me.agents[agent_id] = metadata or {}
        me.incarnation += 1
        self._notify()

    def stop_agent(self, agent_id: str) -> None:
        me = self.members[self.node_id]
        if agent_id in me.agents:
            del me.agents[agent_id]
            me.incarnation += 1
            self._notify()

    def agent_members(self, agent_id: str) -> Dict[str, dict]:
        """node_id -> metadata for every ALIVE node hosting the agent."""
        return {m.node_id: m.agents[agent_id]
                for m in self.members.values()
                if m.status == ALIVE and agent_id in m.agents}

    def alive_members(self) -> Set[str]:
        return {m.node_id for m in self.members.values()
                if m.status == ALIVE}

    def on_change(self, cb: Callable[[], None]) -> None:
        self._listeners.append(cb)

    def remove_on_change(self, cb: Callable[[], None]) -> None:
        """Deregister a change listener (a stopped consumer — e.g. a
        ClusterView — must not be pinned/driven by the host forever)."""
        try:
            self._listeners.remove(cb)
        except ValueError:
            pass

    def _notify(self) -> None:
        for cb in self._listeners:
            cb()

    # ---------------- wire ------------------------------------------------

    def _send(self, addr: Tuple[str, int], msg: dict) -> None:
        if self.transport is None or self.stopped:
            return
        msg["from"] = self.node_id
        msg["gossip"] = self._gossip_sample()
        try:
            self.transport.sendto(json.dumps(msg).encode(), addr)
        except OSError:
            pass

    def _gossip_sample(self) -> List[dict]:
        members = list(self.members.values())
        self.rng.shuffle(members)
        return [m.record() for m in members[:8]]

    def datagram_received(self, data: bytes, addr: Tuple[str, int]) -> None:
        if self.stopped:
            return
        try:
            msg = json.loads(data.decode())
        except (ValueError, UnicodeDecodeError):
            return
        for rec in msg.get("gossip", []):
            self._merge(rec)
        t = msg.get("t")
        if t == "join":
            self._send(addr, {"t": "welcome"})
        elif t == "ping":
            self._send(addr, {"t": "ack", "seq": msg.get("seq")})
        elif t == "ping-req":
            # indirect probe on behalf of the requester (SWIM k-relay):
            # ping the target with OUR seq and relay the requester's ack
            # only once the TARGET answers — a helper must confirm the
            # target, not merely its own liveness
            target = msg.get("target")
            ts = self.members.get(target)
            if ts is not None:
                self._seq += 1
                self._relays[self._seq] = (addr, msg.get("seq"),
                                           time.time())
                self._send(ts.addr, {"t": "ping", "seq": self._seq})
        elif t == "ack":
            seq = msg.get("seq")
            relay = self._relays.pop(seq, None)
            if relay is not None:       # target answered our relayed ping
                origin_addr, origin_seq, _ts = relay
                self._send(tuple(origin_addr), {"t": "ack",
                                                "seq": origin_seq})
            fut = self._acks.pop(seq, None)
            if fut is not None and not fut.done():
                fut.set_result(True)
        elif t == "payload":
            # application payload channel (CRDT anti-entropy rides the
            # membership transport, ≈ the reference's cluster Messenger)
            cb = self._payload_handlers.get(msg.get("ch"))
            if cb is not None:
                try:
                    cb(msg.get("from"), msg.get("data"))
                except Exception:  # noqa: BLE001
                    log.exception("payload handler failed")

    def _merge(self, rec: dict) -> None:
        nid = rec.get("id")
        if not nid:
            return
        inc, st = rec.get("inc", 0), rec.get("st", ALIVE)
        cur = self.members.get(nid)
        if nid == self.node_id:
            # refute rumors about myself (SWIM refutation)
            me = self.members[self.node_id]
            if st != ALIVE and inc >= me.incarnation:
                me.incarnation = inc + 1
                self._notify()
            return
        changed = False
        if cur is None:
            self.members[nid] = MemberState(
                node_id=nid, addr=tuple(rec.get("addr", ("", 0))),
                incarnation=inc, status=st, agents=rec.get("agents", {}),
                tcp_port=rec.get("tcp", 0))
            changed = True
        else:
            # precedence: higher incarnation wins; at equal incarnation a
            # worse status (suspect/dead) overrides alive
            rank = {ALIVE: 0, SUSPECT: 1, DEAD: 2}
            if (inc > cur.incarnation
                    or (inc == cur.incarnation
                        and rank[st] > rank[cur.status])):
                cur.incarnation = inc
                if cur.status != st:
                    cur.status = st
                    cur.status_at = time.time()
                cur.agents = rec.get("agents", cur.agents)
                cur.tcp_port = rec.get("tcp", cur.tcp_port)
                changed = True
        if changed:
            self._notify()

    # ---------------- SWIM probe loop --------------------------------------

    async def _probe_loop(self) -> None:
        try:
            while not self.stopped:
                await asyncio.sleep(self.PROBE_INTERVAL)
                self._advance_suspicions()
                # relay entries for targets that never ack must not leak
                cutoff = time.time() - 5.0
                for seq in [s for s, (_a, _q, ts) in self._relays.items()
                            if ts < cutoff]:
                    del self._relays[seq]
                target = self._pick_probe_target()
                if target is None:
                    # alone with seeds configured: keep knocking
                    # (≈ AutoSeeder). The startup join is a single UDP
                    # datagram — a seed still booting when it arrived
                    # would otherwise orphan this node forever, and a
                    # view that collapsed to self (mutual reap after a
                    # long stall) could never heal.
                    for seed in self.seeds:
                        self._send(tuple(seed), {"t": "join"})
                    continue
                ok = await self._probe(target)
                if not ok:
                    ok = await self._indirect_probe(target)
                if not ok:
                    self._suspect(target)
        except asyncio.CancelledError:
            pass

    def _pick_probe_target(self) -> Optional[MemberState]:
        candidates = [m for m in self.members.values()
                      if m.node_id != self.node_id and m.status != DEAD]
        return self.rng.choice(candidates) if candidates else None

    async def _probe(self, target: MemberState) -> bool:
        self._seq += 1
        seq = self._seq
        fut = asyncio.get_running_loop().create_future()
        self._acks[seq] = fut
        self._send(target.addr, {"t": "ping", "seq": seq})
        try:
            await asyncio.wait_for(fut, self.PROBE_TIMEOUT)
            return True
        except asyncio.TimeoutError:
            self._acks.pop(seq, None)
            return False

    async def _indirect_probe(self, target: MemberState) -> bool:
        """k-relay probing (≈ FailureDetector.java:54 scaled indirect
        probes): ask K alive helpers to ping the target; ANY relay-
        confirmed ack proves the target alive even when the direct
        requester→target path is partitioned."""
        helpers = [m for m in self.members.values()
                   if m.status == ALIVE
                   and m.node_id not in (self.node_id, target.node_id)]
        self.rng.shuffle(helpers)
        helpers = helpers[:self.INDIRECT_K]
        if not helpers:
            return False
        futs = []
        seqs = []
        for helper in helpers:
            self._seq += 1
            seq = self._seq
            fut = asyncio.get_running_loop().create_future()
            self._acks[seq] = fut
            seqs.append(seq)
            futs.append(fut)
            self._send(helper.addr, {"t": "ping-req", "seq": seq,
                                     "target": target.node_id})
        done, pending = await asyncio.wait(
            futs, timeout=self.PROBE_TIMEOUT * 2,
            return_when=asyncio.FIRST_COMPLETED)
        for seq in seqs:
            self._acks.pop(seq, None)
        return bool(done)

    def _suspect(self, target: MemberState) -> None:
        if target.status == ALIVE:
            target.status = SUSPECT
            target.status_at = time.time()
            self._notify()
            self._broadcast_state(target)

    def _advance_suspicions(self) -> None:
        now = time.time()
        for m in list(self.members.values()):
            if m.node_id == self.node_id:
                continue
            if m.status == SUSPECT and now - m.status_at > self.SUSPECT_TIMEOUT:
                m.status = DEAD   # ≈ AutoDropper eviction
                m.status_at = now
                self._notify()
                self._broadcast_state(m)
            elif m.status == DEAD and now - m.status_at > self.DEAD_REAP:
                del self.members[m.node_id]

    def _broadcast_state(self, member: MemberState) -> None:
        peers = [m for m in self.members.values()
                 if m.status == ALIVE and m.node_id != self.node_id]
        self.rng.shuffle(peers)
        for peer in peers[:self.GOSSIP_FANOUT]:
            self._send(peer.addr, {"t": "state"})
