"""Retry/backoff policy + per-call deadline budgets + idempotency whitelist.

The reference leans on gRPC's deadline propagation and service-config
retries (base-rpc, SURVEY.md §2.4). Here:

- ``RetryPolicy``: exponential backoff with FULL jitter (AWS architecture
  blog discipline: sleep = uniform(0, min(cap, base * mult**attempt))) —
  retry storms decorrelate instead of synchronizing.
- Deadline budgets: a caller opens ``deadline_scope(budget_s)``; every RPC
  issued inside the scope caps its timeout at the remaining budget AND
  stamps the remainder into the request header (u32 milliseconds), so a
  downstream handler inherits the shrunken budget across process hops —
  gRPC ``grpc-timeout`` semantics re-expressed.
- Idempotency whitelist: only (service, method) pairs registered safe —
  RO coproc queries (match), registry/meta lookups — auto-retry after an
  AMBIGUOUS transport failure (the request may have executed server-side).
  Unlisted methods fail fast to the caller, who owns the ambiguity.
"""

from __future__ import annotations

import contextlib
import contextvars
import random
import time
from dataclasses import dataclass
from typing import Iterator, Optional, Set, Tuple

# ---------------------------------------------------------------------------
# deadline budget propagation (≈ gRPC deadline / grpc-timeout header)
# ---------------------------------------------------------------------------

# absolute time.monotonic() deadline for the current logical call tree
_DEADLINE: contextvars.ContextVar[Optional[float]] = contextvars.ContextVar(
    "rpc_deadline", default=None)


def current_deadline() -> Optional[float]:
    """Absolute ``time.monotonic()`` deadline of the active scope (None =
    unbounded)."""
    return _DEADLINE.get()


def remaining_budget() -> Optional[float]:
    """Seconds left in the active deadline scope; None = unbounded.
    Clamped at 0.0 — an exhausted budget never goes negative."""
    d = _DEADLINE.get()
    if d is None:
        return None
    return max(0.0, d - time.monotonic())


@contextlib.contextmanager
def deadline_scope(budget_s: Optional[float]) -> Iterator[Optional[float]]:
    """Bound everything inside to ``budget_s`` seconds from now. Nested
    scopes only ever SHRINK the deadline (a callee cannot outlive its
    caller's budget). ``None`` is a no-op passthrough."""
    if budget_s is None:
        yield _DEADLINE.get()
        return
    new = time.monotonic() + budget_s
    cur = _DEADLINE.get()
    if cur is not None:
        new = min(new, cur)
    token = _DEADLINE.set(new)
    try:
        yield new
    finally:
        _DEADLINE.reset(token)


@contextlib.contextmanager
def absolute_deadline(deadline: Optional[float]) -> Iterator[None]:
    """Install an ABSOLUTE monotonic deadline (server side: re-arm the
    scope from a decoded wire header). Shrink-only, like deadline_scope."""
    if deadline is None:
        yield
        return
    cur = _DEADLINE.get()
    if cur is not None:
        deadline = min(deadline, cur)
    token = _DEADLINE.set(deadline)
    try:
        yield
    finally:
        _DEADLINE.reset(token)


# ---------------------------------------------------------------------------
# idempotency whitelist
# ---------------------------------------------------------------------------

# (service, method); method "*" whitelists a whole service. Seeded with the
# RO surfaces that are safe to re-issue after an ambiguous failure: match
# queries never mutate, session-dict presence checks are reads. Route
# mutations are NOT listed even though the incarnation guards make them
# mostly idempotent — the caller decides. (The basekv client deliberately
# bypasses this whitelist: ClusterKVClient._call is its own at-least-once
# leader-rerouting loop.)
_IDEMPOTENT: Set[Tuple[str, str]] = {
    ("dist-worker", "match_batch"),
    ("dist-worker", "node_id"),
    ("dist-worker", "trace_spans"),
    # ISSUE 12: the replication fabric is read-only + cursor-idempotent
    # end to end (re-delivered records drop on the applier's seq cursor)
    ("dist-worker", "repl_fetch"),
    ("dist-worker", "repl_base"),
    ("dist-worker", "repl_inval"),
    ("dist-worker", "repl_status"),
    ("session-dict", "exist"),
    ("session-dict", "clients"),
    ("session-dict", "inbox_state"),
    # the federated observability plane (ISSUE 5) is read-only end to end
    ("cluster-obs", "*"),
}


def register_idempotent(service: str, method: str = "*") -> None:
    _IDEMPOTENT.add((service, method))


def unregister_idempotent(service: str, method: str = "*") -> None:
    _IDEMPOTENT.discard((service, method))


def is_idempotent(service: str, method: str) -> bool:
    return ((service, method) in _IDEMPOTENT
            or (service, "*") in _IDEMPOTENT)


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff + full jitter, bounded by attempts AND budget."""

    max_attempts: int = 4          # total tries (1 = no retry)
    base_delay: float = 0.02       # first-retry backoff cap (seconds)
    max_delay: float = 1.0         # per-retry backoff ceiling
    multiplier: float = 2.0

    def _cap(self, attempt: int) -> float:
        """Worst-case backoff before retry ``attempt`` — the ONE place
        the growth curve lives (backoff() jitters under it, should_retry()
        checks it fits the budget)."""
        return min(self.max_delay,
                   self.base_delay * (self.multiplier ** (attempt - 1)))

    def backoff(self, attempt: int,
                rng: Optional[random.Random] = None) -> float:
        """Sleep before retry ``attempt`` (1-based: attempt 1 = first
        retry). Full jitter: uniform over (0, cap]."""
        r = rng.random() if rng is not None else random.random()
        return self._cap(attempt) * r

    def should_retry(self, attempt: int) -> bool:
        """More attempts allowed after ``attempt`` failures, within the
        active deadline budget: the next retry's worst-case backoff must
        still FIT the remaining budget — sleeping past the deadline just
        converts the genuine endpoint failure into a budget-exhaustion
        timeout one attempt later."""
        if attempt >= self.max_attempts:
            return False
        rem = remaining_budget()
        return rem is None or rem > self._cap(attempt)


DEFAULT_RETRY_POLICY = RetryPolicy()
