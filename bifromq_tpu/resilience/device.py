"""Device-fault resilience plane (ISSUE 7 tentpole).

PR 6 moved the serving hot path onto the accelerator; this module makes
the accelerator a survivable dependency instead of a single point of
hang. One failure taxonomy threads through the pipeline, matcher,
worker, scheduler, and obs layers:

- **timeout** — ``DispatchRing.wait_ready`` gains a watchdog deadline
  (``BIFROMQ_DEVICE_DEADLINE_S``, default derived from the live
  dispatch-stage p99 via ``utils.metrics.STAGES``) raising
  :class:`DeviceTimeoutError`; the timed-out slot is reclaimed and its
  orphaned result arrays are parked in a :class:`BufferQuarantine`
  until the device actually finishes with them (donated buffers must
  never be reused mid-flight), while the batch re-routes to the host
  oracle.
- **breaker** — every ``TpuMatcher`` carries a per-device circuit
  breaker (the PR 1 ``resilience/breaker.py`` state machine, fed by
  device timeouts/errors). Open ⇒ matches skip dispatch entirely and
  serve the exact host-oracle degraded path; half-open ⇒ a single
  canary batch probes the device and re-closes only on row-parity
  success. The :class:`DeviceBreakerBoard` joins the breakers to the
  ``/metrics`` ``fabric.breakers`` section and the PR 5 gossip health
  digest so peers demote a device-sick node before routing to it.
- **shed** — when ring pressure (``obs.device.queue_pressure()``) plus
  batcher queue depth exceed a bound, QoS0 publishes shed with
  per-tenant fairness: noisy tenants (PR 3 detector) shed first, and
  only a deeper overload sheds everyone. QoS1 never sheds — it
  backpressures through the bounded :class:`IngestGate` instead of
  queueing without bound.
- **drain** — shutdown/compaction waits bounded for in-flight ring
  slots (``BIFROMQ_DRAIN_TIMEOUT_S``) then gives up cleanly.

Layering: this module may be imported by ``models``/``mqtt``/``dist``;
it must not import ``obs`` or ``utils.metrics`` at module level (the
exporter already imports ``resilience`` — all hub access is lazy, the
same discipline as ``breaker._meter``).
"""

from __future__ import annotations

import asyncio
import threading
import time
import weakref
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

from ..utils.env import env_bool, env_float as _env_float, env_opt_str
from .breaker import CircuitBreaker

#: severity order shared with utils.metrics.FabricMetrics
_SEVERITY = {"closed": 0, "half_open": 1, "open": 2}


class DeviceTimeoutError(Exception):
    """A device dispatch failed to become ready within the watchdog
    deadline: the accelerator is hung, saturated past its budget, or the
    tunnel is gone. Carries the deadline so degraded-path telemetry can
    say how long we waited."""

    def __init__(self, deadline_s: float, detail: str = "") -> None:
        super().__init__(
            f"device not ready within {deadline_s:.3f}s{detail}")
        self.deadline_s = deadline_s


# watchdog bounds: the derived deadline never drops below the floor (a
# cold STAGES histogram or a sub-ms CPU walk must not turn scheduler
# jitter into timeouts) and never exceeds the ceiling (a pathological
# p99 sample must not disarm the watchdog)
DEADLINE_FLOOR_S = 0.25
DEADLINE_CEIL_S = 30.0
DEADLINE_COLD_S = 5.0
#: headroom multiplier over the observed dispatch-stage p99
DEADLINE_P99_FACTOR = 32.0


def _pinned_deadline(env: str) -> Optional[float]:
    """Resolve an explicit deadline pin from ``env``.

    Returns ``(found, value)`` folded into one optional: ``None`` when
    the knob is unset or malformed (callers fall through to their
    derived default), the float otherwise — ``0``/negative disarm
    (``-0.0``... any non-positive), positive pins CLAMP into
    [``DEADLINE_FLOOR_S``, ``DEADLINE_CEIL_S``]. Before ISSUE 16 a
    positive pin passed through unclamped, so ``=0.001`` turned
    scheduler jitter into timeouts and ``=9999`` silently disarmed the
    watchdog; now a nonsensical knob degrades to the nearest sane bound.
    """
    raw = env_opt_str(env)
    if raw is None:
        return None
    try:
        v = float(raw)
    except ValueError:
        return None    # malformed pin ("2s") ⇒ the adaptive derivation,
    if v <= 0:         # same unset-garbage fallback as utils.env helpers
        return float("-inf")   # sentinel: explicit disarm
    return min(DEADLINE_CEIL_S, max(DEADLINE_FLOOR_S, v))


def device_deadline_s() -> Optional[float]:
    """The watchdog deadline for one device batch.

    ``BIFROMQ_DEVICE_DEADLINE_S`` pins it explicitly (``0`` or negative
    disarms the watchdog entirely; positive values clamp to
    [``DEADLINE_FLOOR_S``, ``DEADLINE_CEIL_S``]). Unset, it derives
    from the live dispatch-stage p99 in ``STAGES`` (``device.dispatch``
    + ``device.ready``) with generous headroom, clamped the same way;
    before any sample exists the cold-start default applies. The
    derivation is two ≤64 bucket walks — cheap enough per batch, and it
    tracks the deployment (a CPU walk times out in sub-second, the axon
    tunnel gets seconds).
    """
    pinned = _pinned_deadline("BIFROMQ_DEVICE_DEADLINE_S")
    if pinned is not None:
        return None if pinned == float("-inf") else pinned
    from ..utils.metrics import STAGES
    p99_ms = 0.0
    n = 0
    for stage in ("device.dispatch", "device.ready"):
        h = STAGES.hist(stage)
        if h.count:
            n += h.count
            p99_ms += h.percentile_ms(99)
    if n == 0:
        return DEADLINE_COLD_S
    derived = (p99_ms / 1000.0) * DEADLINE_P99_FACTOR
    return min(DEADLINE_CEIL_S, max(DEADLINE_FLOOR_S, derived))


def shard_deadline_s() -> Optional[float]:
    """Per-shard watchdog deadline for ISSUE 16 split mesh dispatch.

    When the mesh step splits into per-fault-domain groups, each group
    waits under ITS OWN deadline so a hang is attributed to the
    offending shard instead of timing out the whole step.
    ``BIFROMQ_SHARD_DEADLINE_S`` pins it (same disarm/clamp contract as
    the device knob); unset, it inherits :func:`device_deadline_s` —
    one group is just a smaller device batch.
    """
    pinned = _pinned_deadline("BIFROMQ_SHARD_DEADLINE_S")
    if pinned is not None:
        return None if pinned == float("-inf") else pinned
    return device_deadline_s()


# ---------------------------------------------------------------------------
# quarantine: orphaned in-flight buffers parked until actually ready
# ---------------------------------------------------------------------------

class BufferQuarantine:
    """Holds the result arrays of timed-out dispatches alive until the
    device reports them ready.

    A timed-out slot's arrays may alias DONATED probe buffers the device
    is still writing: dropping the last reference (or handing the pages
    back to the allocator) mid-flight is use-after-free by another name.
    Parking the whole result object here keeps the buffers pinned;
    ``sweep()`` (called on ring release — O(1) when empty) frees entries
    whose leaves all report ready. A hard age cap bounds the worst case
    of a permanently wedged device: after ``max_age_s`` the entry is
    dropped anyway (at that point the backend is being torn down, not
    raced) and ``expired_total`` records the leak-or-free gamble.
    """

    def __init__(self, max_age_s: float = 300.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.max_age_s = max_age_s
        self._clock = clock
        self._entries: List[tuple] = []    # (res, quarantined_at, tag)
        self._lock = threading.Lock()
        self.quarantined_total = 0
        self.released_total = 0
        self.expired_total = 0
        # ISSUE 15: per-tag lifetime counts (the mesh tags reclaimed
        # batches with the implicated shard, e.g. "mesh:shard3")
        self.quarantined_by_tag: Dict[str, int] = {}

    def __len__(self) -> int:
        return len(self._entries)

    def add(self, res, tag: Optional[str] = None) -> None:
        with self._lock:
            self._entries.append((res, self._clock(), tag))
            self.quarantined_total += 1
            if tag:
                self.quarantined_by_tag[tag] = \
                    self.quarantined_by_tag.get(tag, 0) + 1

    @staticmethod
    def _ready(res) -> bool:
        try:
            for leaf in (res.start, res.count, res.overflow):
                is_ready = getattr(leaf, "is_ready", None)
                if is_ready is not None and not is_ready():
                    return False
        except Exception:  # noqa: BLE001 — a deleted/poisoned buffer is
            return True    # no longer in flight; safe to let go
        return True

    def sweep(self) -> int:
        """Drop every entry whose buffers are ready (or too old to keep
        gambling on). Returns how many were released."""
        if not self._entries:
            return 0
        now = self._clock()
        kept: List[tuple] = []
        freed = 0
        with self._lock:
            for res, at, tag in self._entries:
                if self._ready(res):
                    freed += 1
                    self.released_total += 1
                elif now - at >= self.max_age_s:
                    freed += 1
                    self.expired_total += 1
                else:
                    kept.append((res, at, tag))
            self._entries = kept
        return freed

    def snapshot(self) -> dict:
        out = {"held": len(self._entries),
               "quarantined_total": self.quarantined_total,
               "released_total": self.released_total,
               "expired_total": self.expired_total}
        if self.quarantined_by_tag:
            out["by_tag"] = dict(self.quarantined_by_tag)
        return out


# ---------------------------------------------------------------------------
# device circuit breakers (per matcher), joined to /metrics + gossip
# ---------------------------------------------------------------------------

def device_breaker_enabled() -> bool:
    return env_bool("BIFROMQ_DEVICE_BREAKER", True)


class DeviceBreakerBoard:
    """Process-global registry of per-matcher device breakers.

    Shaped like ``BreakerRegistry`` so ``FabricMetrics.breaker_snapshot``
    (the ``/metrics`` ``fabric.breakers`` section) can merge it, and so
    the cluster digest can gossip the worst state. Matchers are weakly
    held (a test-scoped matcher must not be pinned by telemetry);
    labels are stable per matcher lifetime."""

    def __init__(self) -> None:
        self._breakers: "weakref.WeakValueDictionary[str, CircuitBreaker]" \
            = weakref.WeakValueDictionary()
        self._seq = 0
        self._registered = False

    def create(self, *, failure_threshold: Optional[int] = None,
               recovery_time: Optional[float] = None,
               clock: Callable[[], float] = time.monotonic,
               label: Optional[str] = None) -> CircuitBreaker:
        if failure_threshold is None:
            failure_threshold = int(
                _env_float("BIFROMQ_DEVICE_BREAKER_THRESHOLD", 3))
        if recovery_time is None:
            recovery_time = _env_float(
                "BIFROMQ_DEVICE_BREAKER_RECOVERY_S", 5.0)
        br = CircuitBreaker(failure_threshold=max(1, failure_threshold),
                            recovery_time=recovery_time, clock=clock)
        self._seq += 1
        # ISSUE 15: labeled breakers (the mesh's per-shard fault domains)
        # keep the shard id in the board key so /metrics and the gossip
        # digest can report per-shard state, not just the worst
        key = f"device:{self._seq}" + (f":{label}" if label else "")
        self._breakers[key] = br
        if not self._registered:
            # lazy: utils.metrics imports obs which imports the exporter
            # which imports resilience — registering at import would
            # close the cycle
            from ..utils.metrics import FABRIC
            FABRIC.register_breakers(self)
            self._registered = True
        return br

    def snapshot(self) -> Dict[str, dict]:
        """Non-closed device breakers only: closed is the default, and
        the happy-path ``/metrics`` payload must not grow a row per
        matcher ever constructed."""
        return {label: b.snapshot()
                for label, b in list(self._breakers.items())
                if b.state != "closed"}

    def states(self, include_closed: bool = False) -> Dict[str, str]:
        return {label: b.state
                for label, b in list(self._breakers.items())
                if include_closed or b.state != "closed"}

    def worst_state(self) -> str:
        worst = "closed"
        for b in list(self._breakers.values()):
            s = b.state
            if _SEVERITY.get(s, 0) > _SEVERITY.get(worst, 0):
                worst = s
        return worst


# the process-global board every TpuMatcher's breaker registers into
DEVICE_BREAKERS = DeviceBreakerBoard()


# ---------------------------------------------------------------------------
# fair load shedding under device overload
# ---------------------------------------------------------------------------

class LoadShedder:
    """QoS0 shedding keyed on device-pipeline pressure, tenant-fair.

    The overload score combines the dispatch ring's occupancy pressure
    (``obs.device.queue_pressure()``: (in-flight + parked waiters) /
    ring depth, so a merely-full pipelining ring scores 1.0) with the
    batcher backlog normalized by ``BIFROMQ_SHED_QUEUE_DEPTH``. Two
    thresholds give the fairness ladder:

    - score ≥ ``level1`` (``BIFROMQ_SHED_PRESSURE``, default 1.5):
      shed QoS0 publishes of tenants the PR 3 detector flags NOISY —
      the tenants filling the pipeline pay first;
    - score ≥ 2×``level1``: shed every QoS0 publish — at-most-once
      traffic is the only legal loss under saturation.

    QoS1/2 are never shed here; they backpressure through the
    :class:`IngestGate`. The score is TTL-cached (5 ms) so the per-
    publish cost under load is one clock compare, and exactly zero
    publishes shed while the score stays under the bound — the tier-2
    chaos gate asserts the counters stay silent outside injected
    overload."""

    SCORE_TTL_S = 0.005

    def __init__(self, clock: Callable[[], float] = time.monotonic) -> None:
        # knobs resolve lazily at first use, NOT at construction: the
        # process-global SHEDDER is built when this module is first
        # imported, which is typically BEFORE the embedding broker (or a
        # monkeypatching test) has set its BIFROMQ_* env — every sibling
        # knob in this plane (deadline, drain, breaker) is read at use
        # time and these must not silently differ. Tests that assign
        # level1/queue_depth_bound directly stay pinned.
        self.level1: Optional[float] = None
        self.queue_depth_bound: Optional[float] = None
        self._clock = clock
        self._score = 0.0
        self._score_at = -1e18
        self._lock = threading.Lock()
        self._shed: Dict[str, int] = {}
        self.shed_total = 0

    # -- signal ------------------------------------------------------------

    def _resolve_knobs(self) -> None:
        if self.level1 is None:
            self.level1 = _env_float("BIFROMQ_SHED_PRESSURE", 1.5)
        if self.queue_depth_bound is None:
            self.queue_depth_bound = max(
                1.0, _env_float("BIFROMQ_SHED_QUEUE_DEPTH", 4096.0))

    def overload_score(self) -> float:
        now = self._clock()
        if now - self._score_at < self.SCORE_TTL_S:
            return self._score
        self._resolve_knobs()
        from ..obs import OBS
        score = (OBS.device.queue_pressure()
                 + OBS.device.dispatch_queue_depth()
                 / self.queue_depth_bound)
        self._score = score
        self._score_at = now
        return score

    # -- decision ----------------------------------------------------------

    def should_shed(self, tenant: str, qos: int = 0) -> bool:
        if qos != 0:
            return False
        score = self.overload_score()     # always resolves the knobs
        if score < self.level1:
            return False
        if score < 2 * self.level1:
            from ..obs import OBS
            # ISSUE 20 advisory feed: between level1 and 2×level1 only
            # tenants flagged noisy OR already burning their SLO budget
            # shed — a burning tenant's QoS0 loss is already priced into
            # its budget, so the spend lands where the SLO is lost
            if not (OBS.is_noisy(tenant) or OBS.is_burning(tenant)):
                return False
        self._record(tenant)
        return True

    def _record(self, tenant: str) -> None:
        with self._lock:
            self._shed[tenant] = self._shed.get(tenant, 0) + 1
            self.shed_total += 1
        from ..utils.metrics import FABRIC, FabricMetric
        FABRIC.inc(FabricMetric.MATCH_SHED)

    # -- observability ------------------------------------------------------

    def snapshot(self) -> dict:
        """``/metrics`` ``"shed"`` section: ``match_shed_total`` per
        tenant plus the live overload score and thresholds."""
        self._resolve_knobs()
        with self._lock:
            per_tenant = dict(self._shed)
        return {"match_shed_total": per_tenant,
                "shed_total": self.shed_total,
                "level1": self.level1,
                "queue_depth_bound": self.queue_depth_bound}

    def reset(self) -> None:
        with self._lock:
            self._shed.clear()
            self.shed_total = 0
        self._score = 0.0
        self._score_at = -1e18


SHEDDER = LoadShedder()


# ---------------------------------------------------------------------------
# bounded-slot admission: the shared primitive under the dispatch ring
# and the QoS>0 ingest gate
# ---------------------------------------------------------------------------

class BoundedSlots:
    """Loop-agnostic bounded in-flight admission.

    No asyncio primitive is bound at construction: waiters are plain
    futures created on whichever loop runs the caller, so one instance
    can serve sessions and matchers across loops (and tests can drive it
    with hand-built loops). Cancellation hygiene: a parked waiter
    withdraws itself (a cancelled future is ``done()``, so it must be
    REMOVED — a stale entry would overcount ``waiting``); a waiter that
    was already granted a wake but dies before using it passes the wake
    on so the slot isn't lost. ``DispatchRing`` (models/pipeline.py) and
    :class:`IngestGate` both ride this — the admission machinery must
    not fork into subtly divergent copies."""

    def __init__(self, capacity: int) -> None:
        self.capacity = max(1, capacity)
        self._inflight = 0
        self._waiters: Deque[asyncio.Future] = deque()
        self.peak_inflight = 0
        self.waited_total = 0

    @property
    def in_flight(self) -> int:
        return self._inflight

    @property
    def waiting(self) -> int:
        return len(self._waiters)

    async def acquire(self) -> None:
        while self._inflight >= self.capacity:
            fut = asyncio.get_running_loop().create_future()
            self._waiters.append(fut)
            self.waited_total += 1
            try:
                await fut
            except BaseException:
                if fut in self._waiters:
                    self._waiters.remove(fut)
                elif fut.done() and not fut.cancelled():
                    self._wake_one()
                raise
        self._inflight += 1
        self.peak_inflight = max(self.peak_inflight, self._inflight)

    def _wake_one(self) -> None:
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(None)
                break

    def release(self) -> None:
        self._inflight = max(0, self._inflight - 1)
        self._wake_one()


# ---------------------------------------------------------------------------
# bounded QoS>0 ingest (backpressure instead of unbounded queueing)
# ---------------------------------------------------------------------------

class IngestGate(BoundedSlots):
    """Bounded in-flight QoS>0 publish admissions.

    Under device overload the batcher queue must not absorb unbounded
    at-least-once work: sessions acquiring past the bound PARK (their
    read loop stalls, TCP backpressures the publisher) instead of
    enqueueing — the loss-free counterpart of QoS0 shedding."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        # like LoadShedder's knobs, the env capacity resolves at first
        # acquire, not at module import — the global INGEST_GATE exists
        # before the broker (or a test) sets BIFROMQ_QOS1_INFLIGHT
        self._lazy_env = capacity is None
        super().__init__(capacity if capacity is not None else 1)

    def _resolve_env(self) -> None:
        if self._lazy_env:
            self._lazy_env = False
            self.capacity = max(
                1, int(_env_float("BIFROMQ_QOS1_INFLIGHT", 1024.0)))

    async def acquire(self) -> None:
        self._resolve_env()
        await super().acquire()

    def snapshot(self) -> dict:
        self._resolve_env()
        return {"in_flight": self._inflight, "waiting": len(self._waiters),
                "capacity": self.capacity,
                "peak_in_flight": self.peak_inflight,
                "waited_total": self.waited_total}


INGEST_GATE = IngestGate()


def drain_timeout_s() -> float:
    """Bounded wait for in-flight device slots on shutdown/compaction."""
    return _env_float("BIFROMQ_DRAIN_TIMEOUT_S", 2.0)
