"""Resilience fabric: retry/backoff policies, circuit breakers, fault
injection (SURVEY.md §2.4 — the reference rides gRPC deadlines/retries and
the traffic governor steers clients off dead servers; our asyncio
re-expression provides the same discipline here).

- ``policy``: RetryPolicy (exponential backoff + full jitter), per-call
  deadline budgets propagated across RPC hops, idempotency whitelist.
- ``breaker``: per-endpoint circuit breaker (closed → open → half-open)
  fed by call outcomes; ``ServiceRegistry`` consults it so rendezvous
  hashing fails over around open circuits.
- ``faults``: process-global FaultInjector hooked into the RPC fabric's
  frame I/O (drop/delay/corrupt/error/disconnect by service/method/
  probability) — the TCP fabric's counterpart of
  ``raft.transport.InMemTransport.partition/kill``.
"""

from .breaker import BreakerRegistry, CircuitBreaker
from .faults import FaultInjector, FaultRule, get_injector
from .policy import (DEFAULT_RETRY_POLICY, RetryPolicy, current_deadline,
                     deadline_scope, is_idempotent, register_idempotent,
                     remaining_budget)

__all__ = [
    "BreakerRegistry", "CircuitBreaker", "FaultInjector", "FaultRule",
    "get_injector", "RetryPolicy", "DEFAULT_RETRY_POLICY",
    "current_deadline", "deadline_scope", "remaining_budget",
    "is_idempotent", "register_idempotent",
]
