"""Per-endpoint circuit breakers (closed → open → half-open).

The traffic-governor analog of the reference steering tenants off dead
servers: call outcomes feed a breaker per endpoint; ``ServiceRegistry``
consults the breaker set so rendezvous hashing skips open circuits and
fails over to the next-ranked live server. Transitions are metered through
``utils.metrics.FABRIC``.

State machine (classic Nygard breaker):

- CLOSED: all traffic flows; ``failure_threshold`` CONSECUTIVE transport
  failures trip it open (a status-1 handler error is a *successful* round
  trip — the server is alive — and resets the streak).
- OPEN: picks avoid the endpoint for ``recovery_time`` seconds.
- HALF_OPEN: after recovery_time, a bounded number of probe calls pass;
  one success closes the circuit, one failure re-opens it (with the full
  recovery window again).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


def _meter(metric_name: str) -> None:
    from ..utils.metrics import FABRIC, FabricMetric
    FABRIC.inc(FabricMetric(metric_name))


class CircuitBreaker:
    def __init__(self, *, failure_threshold: int = 5,
                 recovery_time: float = 1.0,
                 half_open_max_probes: int = 1,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.failure_threshold = failure_threshold
        self.recovery_time = recovery_time
        self.half_open_max_probes = half_open_max_probes
        self._clock = clock
        self._state = CLOSED
        self._failures = 0          # consecutive failure streak
        self._opened_at = 0.0
        self._probes_inflight = 0
        # observability
        self.open_count = 0
        self.last_error: Optional[str] = None

    # ---------------- state ------------------------------------------------

    @property
    def state(self) -> str:
        """Current state; lazily advances OPEN → HALF_OPEN by the clock."""
        if (self._state == OPEN
                and self._clock() - self._opened_at >= self.recovery_time):
            self._state = HALF_OPEN
            self._probes_inflight = 0
            _meter("breaker_half_open_total")
        return self._state

    def available(self) -> bool:
        """Non-consuming routing check (used by pick()): may this endpoint
        receive traffic right now? HALF_OPEN counts as available — the
        probe budget is charged by ``allow()`` at call time."""
        return self.state != OPEN

    def allow(self) -> bool:
        """Consuming admission check at call time. HALF_OPEN charges one
        probe slot; excess concurrent probes are refused."""
        return self.admit() != "rejected"

    def admit(self) -> str:
        """Like ``allow()`` but tells the caller WHICH admission it got:
        ``"ok"`` (closed — normal traffic), ``"canary"`` (half-open —
        this call is the probe, and ISSUE 7's device breaker holds it to
        a stricter success bar: oracle row parity), or ``"rejected"``."""
        s = self.state
        if s == CLOSED:
            return "ok"
        if s == OPEN:
            return "rejected"
        if self._probes_inflight >= self.half_open_max_probes:
            return "rejected"
        self._probes_inflight += 1
        return "canary"

    # ---------------- outcome feed -----------------------------------------

    def release_probe(self) -> None:
        """Return an admission charged by ``allow()`` WITHOUT a verdict
        (cancelled call, caller-budget timeout): the probe budget must
        not leak, or a HALF_OPEN breaker wedges refusing forever."""
        if self._probes_inflight > 0:
            self._probes_inflight -= 1

    def record_success(self) -> None:
        s = self._state
        if s == OPEN:
            # a STALE success: the call was admitted before the trip and
            # only now completed. Re-closing here would bypass the
            # recovery window and the half-open probe (for the device
            # breaker, the canary row-parity bar) — the streak that
            # tripped the breaker is better evidence than one straggler.
            return
        if s == HALF_OPEN:
            if self._probes_inflight == 0:
                return   # not the probe's verdict — same straggler case
            _meter("breaker_closed_total")
        self._state = CLOSED
        self._failures = 0
        self._probes_inflight = 0

    def record_failure(self, error: Optional[str] = None) -> None:
        self.last_error = error
        if self._state == HALF_OPEN:
            self._trip()
            return
        self._failures += 1
        if self._state == CLOSED and self._failures >= self.failure_threshold:
            self._trip()

    def _trip(self) -> None:
        self._state = OPEN
        self._opened_at = self._clock()
        self._failures = 0
        self._probes_inflight = 0
        self.open_count += 1
        _meter("breaker_open_total")

    def force_open(self) -> None:
        """Operator/test hook: trip immediately."""
        self._trip()

    def force_close(self) -> None:
        """Operator/test hook: reset to CLOSED immediately, bypassing
        the recovery window (symmetric with ``force_open``; a stray
        ``record_success`` can no longer do this — stale in-flight
        successes are ignored while OPEN)."""
        self._state = CLOSED
        self._failures = 0
        self._probes_inflight = 0

    def snapshot(self) -> dict:
        return {"state": self.state, "failures": self._failures,
                "open_count": self.open_count,
                "last_error": self.last_error}


class BreakerRegistry:
    """One breaker per endpoint address, created lazily with shared
    parameters. The unit ``ServiceRegistry`` routes around."""

    def __init__(self, *, failure_threshold: int = 5,
                 recovery_time: float = 1.0,
                 half_open_max_probes: int = 1,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._kw = dict(failure_threshold=failure_threshold,
                        recovery_time=recovery_time,
                        half_open_max_probes=half_open_max_probes,
                        clock=clock)
        self._breakers: Dict[str, CircuitBreaker] = {}

    def for_endpoint(self, address: str) -> CircuitBreaker:
        b = self._breakers.get(address)
        if b is None:
            b = self._breakers[address] = CircuitBreaker(**self._kw)
        return b

    def available(self, address: str) -> bool:
        b = self._breakers.get(address)
        return True if b is None else b.available()

    def snapshot(self) -> Dict[str, dict]:
        return {addr: b.snapshot() for addr, b in self._breakers.items()}

    def states(self, include_closed: bool = True) -> Dict[str, str]:
        """Compact endpoint → state map (ISSUE 5: the gossip health
        digest). ``include_closed=False`` drops CLOSED entries — absent
        means healthy, keeping the UDP gossip payload small."""
        return {addr: b.state for addr, b in self._breakers.items()
                if include_closed or b.state != CLOSED}
