"""Process-global wire-level fault injection for the RPC fabric.

``raft/transport.py``'s InMemTransport gives raft partition/kill/drop
chaos; this gives the REAL TCP fabric the same surface. Rules match by
(service, method, side) with a probability, and fire one of:

- ``drop``: the frame vanishes (client: request never sent; server:
  request never dispatched → the caller times out).
- ``delay``: the frame is held ``delay`` seconds before proceeding.
- ``corrupt``: payload bytes are mangled (codec robustness).
- ``error``: the call fails immediately (client: synthetic transport
  error; server: status-1 reply; matcher: raised exception).
- ``disconnect``: the underlying connection is torn down mid-call.

The injector is also the chaos hook for NON-wire failure points: the
dist worker consults ``service="tpu-matcher"`` before device dispatch so
tests can force the host-oracle degradation path.

ISSUE 7 adds the DEVICE-side rule set (``service="tpu-device"``), hooked
into the matcher's dispatch/fetch stages and the ring's readiness poll:

- ``error``: the dispatch (method="dispatch") or fetch (method="fetch")
  raises — a crashed kernel / poisoned buffer.
- ``hang``: the dispatched batch NEVER reports ready while the rule
  stays installed — a wedged accelerator; the watchdog deadline is the
  only way out. Removing the rule "un-wedges" the device (the arrays
  were really ready all along), which is exactly how the chaos gate
  drives breaker recovery.
- ``slow``: readiness is withheld for ``delay`` seconds — a saturated
  device / long tunnel RTT.
- ``flaky_ready``: each readiness poll lies "not ready" with the rule's
  probability — a glitchy PJRT buffer query; completion is only delayed,
  never denied.

Everything is deterministic under a seeded ``random.Random``; injected
faults are counted globally (``utils.metrics.FABRIC``) and per rule.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class FaultRule:
    service: str = "*"
    method: str = "*"
    side: str = "*"            # "client" | "server" | "*"
    probability: float = 1.0
    action: str = "error"      # drop | delay | corrupt | error | disconnect
    delay: float = 0.0         # seconds, for action="delay"
    max_hits: Optional[int] = None   # stop firing after N hits
    hits: int = field(default=0, init=False)

    def matches(self, side: str, service: str, method: str) -> bool:
        if self.max_hits is not None and self.hits >= self.max_hits:
            return False
        return ((self.side in ("*", side))
                and (self.service in ("*", service))
                and (self.method in ("*", method)))


class InjectedFault(Exception):
    """Raised for action="error" at non-wire hook points (e.g. the
    tpu-matcher): carries the rule that fired."""


class FaultInjector:
    def __init__(self, seed: Optional[int] = None) -> None:
        self.rules: List[FaultRule] = []
        self.rng = random.Random(seed)
        self.enabled = False
        self.injected_total = 0

    # ---------------- configuration ----------------------------------------

    def add_rule(self, **kw) -> FaultRule:
        rule = FaultRule(**kw)
        self.rules.append(rule)
        self.enabled = True
        return rule

    def remove_rule(self, rule: FaultRule) -> None:
        if rule in self.rules:
            self.rules.remove(rule)
        self.enabled = bool(self.rules)

    def reset(self, seed: Optional[int] = None) -> None:
        self.rules.clear()
        self.enabled = False
        self.injected_total = 0
        if seed is not None:
            self.rng = random.Random(seed)

    # ---------------- decision points --------------------------------------

    def decide(self, side: str, service: str, method: str,
               actions: Optional[tuple] = None) -> Optional[FaultRule]:
        """First matching rule that fires, or None. ``actions`` restricts
        which rule actions a hook point can honor — rules it cannot act
        on are left untouched (hits/counters unconsumed) for the hook
        that can. O(1) when disabled — the hot path pays a single
        attribute check."""
        if not self.enabled:
            return None
        for rule in self.rules:
            if actions is not None and rule.action not in actions:
                continue
            if rule.matches(side, service, method) \
                    and self.rng.random() < rule.probability:
                rule.hits += 1
                self.injected_total += 1
                self._meter()
                return rule
        return None

    def check_raise(self, side: str, service: str, method: str) -> None:
        """Non-wire hook: raise InjectedFault when an ``error`` rule fires
        (other actions are meaningless without a frame and are NOT
        consumed — they stay armed for the wire hooks)."""
        if self.decide(side, service, method,
                       actions=("error",)) is not None:
            raise InjectedFault(f"{service}/{method} ({side})")

    #: the device-side action taxonomy (ISSUE 7) — see module docstring
    DEVICE_ACTIONS = ("error", "hang", "slow", "flaky_ready")

    def device_rule(self, method: str) -> Optional[FaultRule]:
        """Device-fault hook for ``service="tpu-device"`` rules at the
        matcher's dispatch/fetch stages. ``error`` rules raise here; the
        readiness-shaping actions (hang/slow/flaky_ready) return the
        fired rule for the caller to thread into ``wait_ready``. O(1)
        when the injector is disabled."""
        rule = self.decide("device", "tpu-device", method,
                           actions=self.DEVICE_ACTIONS)
        if rule is not None and rule.action == "error":
            raise InjectedFault(f"tpu-device/{method} (device)")
        return rule

    def rule_active(self, rule: Optional[FaultRule]) -> bool:
        """Is a previously-fired rule still installed? The hang action
        polls this so REMOVING the rule un-wedges the device mid-wait."""
        return rule is not None and rule in self.rules

    @staticmethod
    def _meter() -> None:
        from ..utils.metrics import FABRIC, FabricMetric
        FABRIC.inc(FabricMetric.FAULTS_INJECTED)

    def corrupt(self, payload: bytes) -> bytes:
        """Flip a byte (or fabricate one for empty payloads)."""
        if not payload:
            return b"\xff"
        i = self.rng.randrange(len(payload))
        return payload[:i] + bytes([payload[i] ^ 0xFF]) + payload[i + 1:]


# the process-global injector the fabric consults (tests reconfigure it;
# production leaves it disabled — one bool check per frame)
_INJECTOR = FaultInjector()


def get_injector() -> FaultInjector:
    return _INJECTOR
