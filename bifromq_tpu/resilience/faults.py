"""Process-global wire-level fault injection for the RPC fabric.

``raft/transport.py``'s InMemTransport gives raft partition/kill/drop
chaos; this gives the REAL TCP fabric the same surface. Rules match by
(service, method, side) with a probability, and fire one of:

- ``drop``: the frame vanishes (client: request never sent; server:
  request never dispatched → the caller times out).
- ``delay``: the frame is held ``delay`` seconds before proceeding.
- ``corrupt``: payload bytes are mangled (codec robustness).
- ``error``: the call fails immediately (client: synthetic transport
  error; server: status-1 reply; matcher: raised exception).
- ``disconnect``: the underlying connection is torn down mid-call.

The injector is also the chaos hook for NON-wire failure points: the
dist worker consults ``service="tpu-matcher"`` before device dispatch so
tests can force the host-oracle degradation path.

ISSUE 7 adds the DEVICE-side rule set (``service="tpu-device"``), hooked
into the matcher's dispatch/fetch stages and the ring's readiness poll:

- ``error``: the dispatch (method="dispatch") or fetch (method="fetch")
  raises — a crashed kernel / poisoned buffer.
- ``hang``: the dispatched batch NEVER reports ready while the rule
  stays installed — a wedged accelerator; the watchdog deadline is the
  only way out. Removing the rule "un-wedges" the device (the arrays
  were really ready all along), which is exactly how the chaos gate
  drives breaker recovery.
- ``slow``: readiness is withheld for ``delay`` seconds — a saturated
  device / long tunnel RTT.
- ``flaky_ready``: each readiness poll lies "not ready" with the rule's
  probability — a glitchy PJRT buffer query; completion is only delayed,
  never denied.

Everything is deterministic under a seeded ``random.Random``; injected
faults are counted globally (``utils.metrics.FABRIC``) and per rule.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence


@dataclass
class FaultRule:
    service: str = "*"
    method: str = "*"
    side: str = "*"            # "client" | "server" | "*"
    probability: float = 1.0
    action: str = "error"      # drop | delay | corrupt | error | disconnect
    delay: float = 0.0         # seconds, for action="delay"
    max_hits: Optional[int] = None   # stop firing after N hits
    hits: int = field(default=0, init=False)

    def matches(self, side: str, service: str, method: str) -> bool:
        if self.max_hits is not None and self.hits >= self.max_hits:
            return False
        return ((self.side in ("*", side))
                and (self.service in ("*", service))
                and (self.method in ("*", method)))


class InjectedFault(Exception):
    """Raised for action="error" at non-wire hook points (e.g. the
    tpu-matcher): carries the rule that fired."""


class FaultInjector:
    def __init__(self, seed: Optional[int] = None) -> None:
        self.rules: List[FaultRule] = []
        self.rng = random.Random(seed)
        self.enabled = False
        self.injected_total = 0

    # ---------------- configuration ----------------------------------------

    def add_rule(self, **kw) -> FaultRule:
        rule = FaultRule(**kw)
        self.rules.append(rule)
        self.enabled = True
        return rule

    def remove_rule(self, rule: FaultRule) -> None:
        if rule in self.rules:
            self.rules.remove(rule)
        self.enabled = bool(self.rules)

    def reset(self, seed: Optional[int] = None) -> None:
        self.rules.clear()
        self.enabled = False
        self.injected_total = 0
        if seed is not None:
            self.rng = random.Random(seed)

    # ---------------- decision points --------------------------------------

    def decide(self, side: str, service: str, method: str,
               actions: Optional[tuple] = None) -> Optional[FaultRule]:
        """First matching rule that fires, or None. ``actions`` restricts
        which rule actions a hook point can honor — rules it cannot act
        on are left untouched (hits/counters unconsumed) for the hook
        that can. O(1) when disabled — the hot path pays a single
        attribute check."""
        if not self.enabled:
            return None
        for rule in self.rules:
            if actions is not None and rule.action not in actions:
                continue
            if rule.matches(side, service, method) \
                    and self.rng.random() < rule.probability:
                rule.hits += 1
                self.injected_total += 1
                self._meter()
                return rule
        return None

    def check_raise(self, side: str, service: str, method: str) -> None:
        """Non-wire hook: raise InjectedFault when an ``error`` rule fires
        (other actions are meaningless without a frame and are NOT
        consumed — they stay armed for the wire hooks)."""
        if self.decide(side, service, method,
                       actions=("error",)) is not None:
            raise InjectedFault(f"{service}/{method} ({side})")

    #: the device-side action taxonomy (ISSUE 7) — see module docstring
    DEVICE_ACTIONS = ("error", "hang", "slow", "flaky_ready")

    def device_rule(self, method: str) -> Optional[FaultRule]:
        """Device-fault hook for ``service="tpu-device"`` rules at the
        matcher's dispatch/fetch stages. ``error`` rules raise here; the
        readiness-shaping actions (hang/slow/flaky_ready) return the
        fired rule for the caller to thread into ``wait_ready``. O(1)
        when the injector is disabled."""
        rule = self.decide("device", "tpu-device", method,
                           actions=self.DEVICE_ACTIONS)
        if rule is not None and rule.action == "error":
            raise InjectedFault(f"tpu-device/{method} (device)")
        return rule

    def rule_active(self, rule: Optional[FaultRule]) -> bool:
        """Is a previously-fired rule still installed? The hang action
        polls this so REMOVING the rule un-wedges the device mid-wait."""
        return rule is not None and rule in self.rules

    @staticmethod
    def _meter() -> None:
        from ..utils.metrics import FABRIC, FabricMetric
        FABRIC.inc(FabricMetric.FAULTS_INJECTED)

    def corrupt(self, payload: bytes) -> bytes:
        """Flip a byte (or fabricate one for empty payloads)."""
        if not payload:
            return b"\xff"
        i = self.rng.randrange(len(payload))
        return payload[:i] + bytes([payload[i] ^ 0xFF]) + payload[i + 1:]


# the process-global injector the fabric consults (tests reconfigure it;
# production leaves it disabled — one bool check per frame)
_INJECTOR = FaultInjector()


def get_injector() -> FaultInjector:
    return _INJECTOR


# ---------------------------------------------------------------------------
# chaos campaigns (ISSUE 16 tentpole leg 3)
# ---------------------------------------------------------------------------

@dataclass
class ChaosEvent:
    """One scripted fault transition, fired at a WORKLOAD STEP index —
    step-indexed (not wall-clock) so the same schedule replays the same
    fault sequence on any machine:

    - ``inject``: install a :class:`FaultRule` (``rule_kw`` are the
      ``add_rule`` kwargs) under ``label``;
    - ``clear``: remove the rule installed under ``label`` (absent is a
      no-op — schedules stay valid under reordering edits);
    - ``call``: invoke ``fn(step)`` — the hook for non-rule chaos like
      crashing a standby mid-promote or flapping a tunnel object.
    """

    step: int
    action: str                      # "inject" | "clear" | "call"
    label: str = ""
    rule_kw: Dict = field(default_factory=dict)
    fn: Optional[Callable[[int], None]] = None


class ChaosCampaign:
    """Seeded, scriptable fault schedule driven against a step-indexed
    workload — repeatable fault campaigns instead of one-off chaos
    scripts. The injector is ``reset(seed)`` at campaign start, every
    event fires at a deterministic step boundary, and the report's
    ``signature`` carries only deterministic facts (timeline, rule hit
    counts, per-step workload summaries) so two runs with the same
    seed + schedule compare EQUAL — the blast-radius regression gate.

    The workload callable runs one step and returns a JSON-able summary
    (or None). An optional ``monitor`` (duck-typed —
    :class:`bifromq_tpu.obs.campaign.CampaignMonitor`) is fed after
    every step with the set of live fault labels; its windows/percentile
    report rides the final report under ``"monitor"`` (latency numbers
    excluded from the signature: wall-clock is never deterministic)."""

    def __init__(self, name: str, schedule: Sequence[ChaosEvent], *,
                 seed: int = 0, injector: Optional[FaultInjector] = None,
                 monitor=None) -> None:
        self.name = name
        # stable order: by step, schedule position breaking ties
        self.schedule = sorted(enumerate(schedule),
                               key=lambda kv: (kv[1].step, kv[0]))
        self.seed = seed
        self.injector = injector or get_injector()
        self.monitor = monitor
        self.timeline: List[dict] = []
        self.step_outputs: List = []
        self._live: Dict[str, FaultRule] = {}
        self._all: Dict[str, FaultRule] = {}

    # ---------------- event firing -----------------------------------------

    def _fire(self, ev: ChaosEvent, step: int) -> None:
        if ev.action == "inject":
            label = ev.label or f"rule@{step}"
            rule = self.injector.add_rule(**ev.rule_kw)
            self._live[label] = rule
            self._all[label] = rule
        elif ev.action == "clear":
            rule = self._live.pop(ev.label, None)
            if rule is not None:
                self.injector.remove_rule(rule)
        elif ev.action == "call":
            if ev.fn is not None:
                ev.fn(step)
        else:
            raise ValueError(f"unknown chaos action {ev.action!r}")
        self.timeline.append({"step": step, "action": ev.action,
                              "label": ev.label})

    def _step_events(self, step: int) -> None:
        for _, ev in self.schedule:
            if ev.step == step:
                self._fire(ev, step)

    def _observe(self, step: int) -> None:
        if self.monitor is not None:
            self.monitor.observe_step(step, active=sorted(self._live))

    def _finish(self) -> None:
        # campaigns never leak rules into the next test/campaign
        for rule in self._live.values():
            self.injector.remove_rule(rule)
        self._live.clear()

    # ---------------- drivers ----------------------------------------------

    def run(self, workload: Callable[[int], object],
            n_steps: int) -> dict:
        self.injector.reset(self.seed)
        try:
            for step in range(n_steps):
                self._step_events(step)
                self.step_outputs.append(workload(step))
                self._observe(step)
        finally:
            self._finish()
        return self.report()

    async def arun(self, workload, n_steps: int) -> dict:
        """Async twin of :meth:`run` for workloads that await (the
        async serving plane, standby sync loops)."""
        self.injector.reset(self.seed)
        try:
            for step in range(n_steps):
                self._step_events(step)
                self.step_outputs.append(await workload(step))
                self._observe(step)
        finally:
            self._finish()
        return self.report()

    # ---------------- report -----------------------------------------------

    def report(self) -> dict:
        sig = {"name": self.name, "seed": self.seed,
               "timeline": list(self.timeline),
               "rule_hits": {lbl: r.hits for lbl, r in self._all.items()},
               "steps": [out for out in self.step_outputs]}
        out = {"signature": sig,
               "injected_total": self.injector.injected_total}
        if self.monitor is not None:
            mon = self.monitor.report()
            # the monitor's deterministic half joins the signature; its
            # latency numbers stay outside (wall-clock)
            sig["windows"] = mon.get("windows")
            sig["degradation"] = mon.get("steps")
            out["monitor"] = mon
        return out
